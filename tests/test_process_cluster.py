"""Process-per-replica harness: cross-shard transactions over real processes.

The non-skipping counterpart to ``test_parallel_multiproc.py``: that test
needs jaxlib multiprocess collectives (absent on bare CPU images and skipped
with the runtime's own words); this one exercises the repo's OWN
multi-process path — ``testing/process_cluster.ProcessCluster`` spawning
``python -m mochi_tpu.server`` children — so the shard-per-core deployment
surface is covered on every CI image.

What is pinned here, per the config-8 acceptance criteria:

* a transaction spanning two shards (two keys with different token-ring
  replica sets) commits atomically — both shards serve the written values;
* the same holds with one owning replica SIGKILLed between grant assembly
  and the Write2 dispatch (f=1 within that shard's replica set);
* when a shard has lost its quorum, the cross-shard transaction aborts on
  BOTH shards (no Write2 is ever dispatched, so the healthy shard stays
  unwritten);
* SIGTERM teardown is a graceful drain: every child exits 0.
"""

from __future__ import annotations

import asyncio
from typing import Tuple

import pytest

from mochi_tpu.client.client import MochiDBClient
from mochi_tpu.client.errors import RequestRefused
from mochi_tpu.client.txn import TransactionBuilder
from mochi_tpu.testing import ProcessCluster


def _cross_shard_keys(config, prefix: str = "ps") -> Tuple[str, str, str]:
    """Two keys with different replica sets, plus a replica that owns the
    first key but NOT the second (the f=1 kill victim: its loss leaves the
    second shard's set whole and the first with exactly a quorum)."""
    for i in range(4096):
        k1 = f"{prefix}-a-{i}"
        s1 = set(config.replica_set_for_key(k1))
        for j in range(4096):
            k2 = f"{prefix}-b-{j}"
            s2 = set(config.replica_set_for_key(k2))
            if s2 != s1 and (s1 - s2):
                return k1, k2, sorted(s1 - s2)[0]
    raise AssertionError("no cross-shard key pair found (ring degenerate?)")


def test_cross_shard_transaction_two_processes():
    """Satellite: 2 replica processes, one cross-shard commit, both shards
    serve reads — runs on bare CI images (no jax collectives involved)."""

    async def body():
        async with ProcessCluster(n_servers=6, rf=4, n_processes=2) as pc:
            k1, k2, _ = _cross_shard_keys(pc.config)
            client = pc.client(timeout_s=8.0)
            await client.execute_write_transaction(
                TransactionBuilder().write(k1, b"v1").write(k2, b"v2").build()
            )
            # Both shards serve the committed values — separate reads, so
            # each is answered by its own replica set's quorum.
            r1 = await client.execute_read_transaction(
                TransactionBuilder().read(k1).build()
            )
            r2 = await client.execute_read_transaction(
                TransactionBuilder().read(k2).build()
            )
            assert r1.operations[0].value == b"v1"
            assert r2.operations[0].value == b"v2"
            pc.check_alive()
        # graceful drain: TERM'd children exit 0, never a mid-batch abort
        assert set(pc.returncodes.values()) == {0}, pc.returncodes

    asyncio.run(asyncio.wait_for(body(), timeout=120))


def test_cross_shard_commit_survives_replica_kill_mid_write2():
    """f=1 within one shard's replica set: an owning replica SIGKILLed
    after grants are assembled but before Write2 dispatches — the
    transaction still commits on BOTH shards (quorum 2f+1 survives)."""

    async def body():
        # process-per-replica so the SIGKILL takes down exactly one replica
        async with ProcessCluster(n_servers=6, rf=4, n_processes=6) as pc:
            k1, k2, victim = _cross_shard_keys(pc.config)
            client = pc.client(timeout_s=8.0)
            # warm sessions/connections off the fault path
            await client.execute_write_transaction(
                TransactionBuilder().write(k1, b"w").write(k2, b"w").build()
            )

            orig_write2 = MochiDBClient._write2
            killed = []

            async def kill_then_write2(self, transaction, certificate, tt=None):
                if not killed:
                    killed.append(pc.kill_replica(victim))
                    await asyncio.sleep(0.05)  # let the SIGKILL land
                return await orig_write2(self, transaction, certificate, tt)

            client._write2 = kill_then_write2.__get__(client)
            await client.execute_write_transaction(
                TransactionBuilder().write(k1, b"v1").write(k2, b"v2").build()
            )
            assert killed, "fault injection never fired"
            client._write2 = orig_write2.__get__(client)

            r1 = await client.execute_read_transaction(
                TransactionBuilder().read(k1).build()
            )
            r2 = await client.execute_read_transaction(
                TransactionBuilder().read(k2).build()
            )
            assert r1.operations[0].value == b"v1"
            assert r2.operations[0].value == b"v2"

    asyncio.run(asyncio.wait_for(body(), timeout=180))


def test_cross_shard_abort_when_one_shard_lost_quorum():
    """Beyond f within one shard: the cross-shard transaction aborts on
    BOTH shards — client-coordinated 2PC never dispatches Write2 without
    per-key quorum grants, so the healthy shard stays unwritten."""

    async def body():
        async with ProcessCluster(n_servers=6, rf=4, n_processes=6) as pc:
            k1, k2, _ = _cross_shard_keys(pc.config)
            s1 = set(pc.config.replica_set_for_key(k1))
            s2 = set(pc.config.replica_set_for_key(k2))
            client = pc.client(timeout_s=4.0, write_attempts=3, refusal_retries=1)
            # Choose two k1-owning victims, preferring replicas OUTSIDE
            # k2's set so its quorum stays intact (overlapping ring
            # windows may force one overlap; rf=4 tolerates f=1).
            only_s1 = sorted(s1 - s2)
            victims = (only_s1 + sorted(s1 & s2))[:2]
            assert len(set(s2) - set(victims)) >= pc.config.quorum, (
                "test setup would break the healthy shard's quorum too"
            )
            for v in victims:
                pc.kill_replica(v)
            await asyncio.sleep(0.1)
            with pytest.raises(RequestRefused):
                await client.execute_write_transaction(
                    TransactionBuilder().write(k1, b"v1").write(k2, b"v2").build()
                )
            # aborts on both: the healthy shard never saw a Write2
            r2 = await client.execute_read_transaction(
                TransactionBuilder().read(k2).build()
            )
            assert not r2.operations[0].existed

    asyncio.run(asyncio.wait_for(body(), timeout=180))

"""Seeded regression fixture: every call here must trip async-blocking."""

import time
import subprocess
from mochi_tpu.crypto import keys


async def handler(seed, msg):
    time.sleep(0.1)  # blocking sleep on the loop
    with open("/tmp/x") as fh:  # blocking builtin IO
        fh.read()
    subprocess.run(["true"])  # blocking subprocess
    return keys.sign(seed, msg)  # host crypto on the loop

"""Seeded violations for the span-lazy-label rule: eager string formatting
in span-record arguments on the (simulated) drain hot loop."""
import time


class Tracer:
    def record(self, name, ctx, t0, dur, args=None):
        pass

    def span(self, name, ctx):
        pass


tracer = Tracer()


def drain(envs, ctx):
    t0 = time.time()
    for i, env in enumerate(envs):
        # BAD: f-string label evaluated per envelope, sampled or not
        tracer.record(f"drain.env-{i}", ctx, t0, 0.0)
        # BAD: %-format in an args value
        tracer.record("drain", ctx, t0, 0.0, args={"peer": "peer-%s" % env})
        # BAD: .format() label
        tracer.record("drain.{}".format(env), ctx, t0, 0.0)
        # BAD: string concatenation label
        tracer.span("drain." + str(i), ctx)

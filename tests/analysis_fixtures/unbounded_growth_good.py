"""Mirror of unbounded_growth_bad.py: every container either shows
eviction evidence or is bounded by construction — all clean."""

from collections import deque


class CappedTable:
    def __init__(self):
        self.sessions = {}
        self.stats = {}
        self.backlog = deque(maxlen=1024)  # bounded by construction
        self.ring = []

    # len() cap check is eviction evidence
    def open_session(self, client_id, session):
        if len(self.sessions) >= 4096:
            self.sessions.pop(next(iter(self.sessions)))
        self.sessions[client_id] = session

    # explicit del elsewhere in the class counts for the whole attr
    def record(self, envelope):
        cid = envelope.client_id
        self.stats[cid] = self.stats.get(cid, 0) + 1

    def forget(self, cid):
        del self.stats[cid]

    def enqueue(self, frame):
        self.backlog.append(frame)

    # rotation (reassignment outside __init__) is eviction evidence
    def absorb(self, batch):
        for env in batch:
            self.ring.append(env)
        self.ring = self.ring[-256:]


class NotPerRequest:
    def __init__(self, server_ids):
        self.peers = {}
        # growth in __init__ is setup, not per-request
        for sid in server_ids:
            self.peers[sid] = None

    # growth keyed by a constant, not request-derived data: clean
    def mark(self, flag):
        self.peers["local"] = True

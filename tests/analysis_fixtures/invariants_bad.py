"""Seeded regression fixture: both rules of protocol-invariants must trip."""

from dataclasses import dataclass


@dataclass(frozen=True)
class PingToServer:
    message: str = "ping"


@dataclass(frozen=True)
class ForgottenFromServer:  # defined but NOT registered below
    message: str = "oops"


_PAYLOAD_TYPES = (
    PingToServer,
)


def quorum_of(f: int) -> int:
    return 2 * f + 1  # inline quorum arithmetic

"""Counterpart fixture: none of these may trip protocol-invariants."""

from dataclasses import dataclass


@dataclass(frozen=True)
class PingToServer:
    message: str = "ping"


@dataclass(frozen=True)
class PongFromServer:
    message: str = "pong"


class GrantHelper:  # not a message class: suffix doesn't match
    pass


_PAYLOAD_TYPES = (
    PingToServer,
    PongFromServer,
)


def quorum_of(config) -> int:
    return config.quorum  # the single source of BFT math


def unrelated_arithmetic(n: int) -> int:
    return 2 * n + 1  # not the quorum shape: operand is not `f`

"""Seeded unbounded-growth regressions: per-identity keyed containers
with no eviction anywhere in the class (the SessionTable/client_stats/
ban-book bug class PRs 8/9 fixed by hand)."""

from collections import defaultdict, deque


class LeakyTable:
    def __init__(self):
        self.sessions = {}
        self.stats = defaultdict(int)
        self.backlog = deque()

    # 1. dict subscript keyed straight off a request parameter
    def open_session(self, client_id, session):
        self.sessions[client_id] = session

    # 2. defaultdict grown via a name derived from a parameter
    def record(self, envelope):
        cid = envelope.client_id
        self.stats[cid] = self.stats.get(cid, 0) + 1

    # 3. capless deque .append of per-request data
    def enqueue(self, frame):
        self.backlog.append(frame)


class LoopDerived:
    def __init__(self):
        self.seen = {}

    # 4. key bound by iterating a parameter (transitive derivation)
    def absorb(self, batch):
        for env in batch:
            self.seen[env.msg_id] = env

"""Seeded wire-taint regressions: every block reaches a protocol-decision
sink with a wire-tainted value and no sanctioned verifier edge on the
path.  tests/test_analysis_checkers.py pins the exact conviction count;
tests/test_static_analysis.py runs the file through the CLI exit-code
gate.  Mirror image of wire_taint_good.py (same flows, verifiers added).
"""

from mochi_tpu.protocol import codec  # noqa: F401  (patterns are suffix-matched)


class BadReplica:
    # 1. direct: decoded envelope straight into the write1 apply
    def on_frame(self, frame, store):
        env = codec.decode_env(frame)
        return store.process_write1(env)

    # 2. entry edge: handle_batch params arrive off the transport tainted
    async def handle_batch(self, envs, store):
        for env in envs:
            store.process_read(env)

    # 3. interprocedural: the taint crosses a helper's return value
    def _pull(self, sock):
        resp = sock.send_and_receive(b"req")
        return resp

    def on_reply(self, sock):
        resp = self._pull(sock)
        self._tally_write2(resp)

    # 4. attr-store sink: WAL records into the reclaimed ledger unverified
    def replay(self, directory):
        for rec in iter_log(directory, "s1"):
            key, ts, gh, epoch = rec.body
            self.reclaimed[(key, ts)] = gh

    # 5. CNF partial: _grant_ok confers cert but grant-subset also
    #    demands env (the envelope MAC gate was skipped)
    def assemble(self, transaction, payloads):
        oks = []
        for p in payloads:
            mg = from_obj(p)
            if self._grant_ok(mg, transaction):
                oks.append(mg)
        return self._quorum_grant_subset(transaction, oks)

"""Counterpart fixture: none of these may trip jax-trace-safety."""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def shape_branch(x: jnp.ndarray) -> jnp.ndarray:
    # static-shape branching selects kernel variants — exempt
    if len(x.shape) == 2:
        return x
    if x.dtype == jnp.int32:
        return x
    for i in range(x.shape[0]):
        x = x + i
    return x


def branchless(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(x > 0, x, -x)


def host_helper(limbs) -> int:
    # un-annotated host-side helper: numpy/float are its whole job
    arr = np.asarray(limbs)
    return int(arr[0])


@functools.partial(jax.jit, static_argnames=("flag",))
def static_arg_branch(x, flag: bool = False):
    # `flag` is declared static: Python branching on it is the idiom
    if flag:
        return lax.neg(x)
    return x

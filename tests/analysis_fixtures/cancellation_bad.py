"""Seeded regression fixture: every handler here must trip
cancellation-hygiene."""

import asyncio


async def bare_except():
    try:
        await asyncio.sleep(1)
    except:  # noqa: E722 - deliberately bare
        pass


async def base_exception():
    try:
        await asyncio.sleep(1)
    except BaseException:
        pass


async def tuple_swallow(task):
    task.cancel()
    try:
        await task
    except (asyncio.CancelledError, Exception):
        pass


async def broad_no_cancel_sibling():
    try:
        await asyncio.sleep(1)
    except Exception:
        pass

"""Mirror of wire_taint_bad.py with the sanctioned verifier edges in
place: every flow below is clean, and the seeded mutation sweep in
tests/test_wire_taint_fixes.py proves non-vacuity by deleting one
verifier call per seed and requiring the pass to convict the sink."""

from mochi_tpu.protocol import codec  # noqa: F401


class GoodReplica:
    # 1. envelope MAC gate before the write1 apply
    def on_frame(self, frame, store):
        env = codec.decode_env(frame)
        if not self._auth_mac(env):
            return None
        return store.process_write1(env)

    # 2. entry edge params verified before the read apply
    async def handle_batch(self, envs, store):
        for env in envs:
            if not self._auth_mac(env):
                continue
            store.process_read(env)

    # 3. interprocedural: the helper's caller authenticates the response
    def _pull(self, sock):
        resp = sock.send_and_receive(b"req")
        return resp

    def on_reply(self, sock):
        resp = self._pull(sock)
        if not self._authentic(resp):
            return
        self._tally_write2(resp)

    # 4. reclaim records re-authenticated before the ledger write
    def replay(self, directory):
        for rec in iter_log(directory, "s1"):
            key, ts, gh, epoch, mac = rec.body
            if not self._reclaim_auth_ok(rec.seq, key, ts, gh, epoch, mac):
                continue
            self.reclaimed[(key, ts)] = gh

    # 5. full CNF: envelope auth AND per-grant verification before the
    #    certificate subset is assembled
    def assemble(self, transaction, payloads):
        oks = []
        for p in payloads:
            mg = from_obj(p)
            if not self._authentic(mg):
                continue
            if self._grant_ok(mg, transaction):
                oks.append(mg)
        return self._quorum_grant_subset(transaction, oks)

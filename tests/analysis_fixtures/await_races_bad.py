"""Seeded await-races violations — one site per sub-rule, line-distinct,
plus a check-then-act hidden inside a ``match`` case body.

Each coroutine reproduces the shape of a real pre-PR-10 bug class (see
docs/ANALYSIS.md): the checker must flag exactly these five sites.
"""

import asyncio


class QuorumTally:  # stand-in: the checker matches the constructor NAME
    def add(self, response):
        pass

    @property
    def chosen(self):
        return None


class Racy:
    def __init__(self):
        self.table = {}
        self.pending = {}
        self.peers = {}

    async def check_then_act(self, key):
        if key in self.table:  # guard runs in await segment 0...
            await asyncio.sleep(0)
            del self.table[key]  # BAD: ...act runs one await later, unverified

    async def stale_read(self, key):
        entry = self.pending.get(key)  # element read out of shared state
        await asyncio.sleep(0)
        return entry.seal()  # BAD: consumed one await later, never re-read

    async def shared_iter(self):
        for peer in self.peers:  # BAD: live shared container, await in body
            await self.ping(peer)

    async def tally_authority(self, responses):
        tally = QuorumTally()
        for response in responses:
            tally.add(response)
        await asyncio.sleep(0)
        return tally.chosen  # BAD: liveness verdict consumed as authority

    async def match_dispatch(self, cmd, key):
        match cmd:
            case "evict":
                if key in self.table:  # guard runs in one segment...
                    await asyncio.sleep(0)
                    del self.table[key]  # BAD: check-then-act inside a case
            case _:
                pass

    async def ping(self, peer):
        await asyncio.sleep(0)

"""Counterpart fixture: none of these may trip cancellation-hygiene."""

import asyncio


async def reraises():
    try:
        await asyncio.sleep(1)
    except Exception:
        raise


async def explicit_cancel_sibling():
    try:
        await asyncio.sleep(1)
    except asyncio.CancelledError:
        raise
    except Exception:
        pass


async def await_cancelled_task(task):
    task.cancel()
    try:
        await task
    except asyncio.CancelledError:
        pass  # the cancellation we just requested
    except Exception:
        pass


async def no_await_in_try():
    try:
        x = 1 / 0  # nothing awaitable: cancellation can't originate here
    except Exception:
        x = 0
    await asyncio.sleep(x)


def sync_function():
    try:
        pass
    except Exception:
        pass

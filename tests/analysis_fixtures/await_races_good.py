"""Safe counterparts for every await-races sub-rule: the checker must stay
silent on all of these (each is the documented remediation idiom)."""

import asyncio


class QuorumTally:
    def add(self, response):
        pass

    @property
    def chosen(self):
        return None


class Careful:
    def __init__(self):
        self.table = {}
        self.pending = {}
        self.peers = {}
        self._lock = asyncio.Lock()

    async def double_checked(self, key):
        if key in self.table:
            await asyncio.sleep(0)
            if key in self.table:  # re-validated in the act's own segment
                del self.table[key]

    async def locked_act(self, key):
        if key in self.table:
            async with self._lock:  # the lock serializes check and act
                del self.table[key]

    async def reread(self, key):
        entry = self.pending.get(key)
        await asyncio.sleep(0)
        entry = self.pending.get(key)  # re-bound after the suspension
        return entry

    async def snapshot_iter(self):
        for peer in list(self.peers):  # snapshot: mutation-safe iteration
            await self.ping(peer)

    async def copy_iter(self):
        for peer in self.peers.copy():  # .copy() is a snapshot too
            await self.ping(peer)

    async def tally_before_await(self, responses):
        tally = QuorumTally()
        for response in responses:
            tally.add(response)
        verdict = tally.chosen  # consumed in the creation segment: fine
        await asyncio.sleep(0)
        return verdict

    async def tuple_rebind(self, key):
        entry = self.pending.get(key)
        entry, rest = (key, None)  # tuple unpack: a FRESH value
        await asyncio.sleep(0)
        return entry, rest  # not stale — rebound before the suspension

    async def loop_rebind(self, rows, key):
        entry = self.pending.get(key)
        for entry in rows:  # loop target: fresh binding each iteration
            pass
        await asyncio.sleep(0)
        return entry

    async def match_revalidated(self, cmd, key):
        match cmd:
            case "evict":
                if key in self.table:
                    await asyncio.sleep(0)
                    if key in self.table:  # re-validated inside the case
                        del self.table[key]
            case str() as fresh_cmd:
                await asyncio.sleep(0)
                return fresh_cmd  # pattern capture: a fresh binding

    async def ping(self, peer):
        await asyncio.sleep(0)

    def sync_mutation(self, key):
        # no awaits — no schedule to race against
        if key in self.table:
            del self.table[key]

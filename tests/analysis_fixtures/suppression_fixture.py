"""Fixture for suppression-comment behavior: two identical violations, one
suppressed inline, one suppressed by the preceding line, one live."""

import time


async def suppressed_inline():
    time.sleep(0.1)  # mochi-lint: disable=async-blocking


async def suppressed_above():
    # mochi-lint: disable=async-blocking
    time.sleep(0.1)


async def live_violation():
    time.sleep(0.1)

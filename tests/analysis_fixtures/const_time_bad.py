"""Seeded regression fixture: every site here must trip constant-time.
(Checked with the path filter off — fixtures live under tests/.)"""


def check_sig(expected_signature: bytes, signature: bytes) -> bool:
    return signature == expected_signature  # timing oracle


def check_mac(mac: bytes, computed_mac: bytes) -> bool:
    if mac != computed_mac:  # timing oracle
        return False
    return True


def check_digest(digest: bytes, other) -> bool:
    return other.digest == digest  # attribute operand, same oracle


def secret_early_return(private_seed: bytes, message: bytes) -> bytes:
    if private_seed[0] & 1:  # secret-dependent early return
        return message
    return message + b"\x00"

"""Counterpart fixture: none of these may trip constant-time."""

import hmac

from mochi_tpu.protocol.messages import FailType


def check_sig(expected_signature: bytes, signature: bytes) -> bool:
    return hmac.compare_digest(signature, expected_signature)


def sig_presence(signature) -> bool:
    # identity/None checks carry no byte content to leak
    return signature is not None


def enum_compare(fail_type) -> bool:
    # ALL-CAPS chain = constant, not authenticator bytes
    return fail_type == FailType.BAD_SIGNATURE


def public_branch(message: bytes, signature: bytes) -> bytes:
    # branching on PUBLIC length is not a secret-dependent return
    if len(message) > 64:
        return message[:64]
    return message

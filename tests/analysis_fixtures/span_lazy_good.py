"""Clean counterparts for the span-lazy-label rule: constant names, plain
values, sampling-gated formatting, and the force_mark exemption."""
import time


class Tracer:
    def record(self, name, ctx, t0, dur, args=None):
        pass

    def force_mark(self, name, ctx, args=None):
        pass

    def wants(self, ctx):
        return ctx is not None and ctx.sampled


tracer = Tracer()


def drain(envs, ctx):
    t0 = time.time()
    for i, env in enumerate(envs):
        # GOOD: constant name, plain-value args — nothing formats eagerly
        tracer.record("drain.env", ctx, t0, 0.0, args={"index": i, "peer": env})
        # GOOD: formatting behind the sampling gate (only paid when the
        # span actually records)
        if tracer.wants(ctx):
            tracer.record(f"drain.env-{i}", ctx, t0, 0.0)
        if ctx is not None and ctx.sampled:
            tracer.record("drain", ctx, t0, 0.0, args={"peer": "p-%s" % env})
        # GOOD: force_mark is the always-sampled upgrade path — it records
        # unconditionally, so eager formatting is paid only on real events
        tracer.force_mark(f"drain.error-{i}", ctx)
        # GOOD: metrics timers are not span records (rule must not trip)
        metrics_record(f"timer-{i}")


def metrics_record(name):
    pass

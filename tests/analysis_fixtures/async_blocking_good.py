"""Counterpart fixture: none of these may trip async-blocking."""

import asyncio
import time
from mochi_tpu.crypto import keys


def sync_helper(seed, msg):
    # blocking calls in a SYNC function are fine (executor fodder)
    time.sleep(0.1)
    with open("/tmp/x") as fh:
        fh.read()
    return keys.sign(seed, msg)


async def handler(seed, msg):
    await asyncio.sleep(0.1)  # the async equivalent

    def _work():
        time.sleep(0.1)  # nested sync def: shipped to the executor
        return keys.sign(seed, msg)

    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, _work)

/* Safe counterparts: the verify path's PUBLIC digit loops and the sign
 * path's branch-free comb — the contrast the checker's fixture pins. */
#include <stdint.h>

/* verify path: ns digits derive from the PUBLIC signature/challenge bytes */
static int public_digits(const uint8_t *sig, const int *TAB, int n) {
    int acc = 0;
    for (int i = 0; i < n; i++) {
        int ns = sig[i] & 15;
        if (ns) { /* public data — branching is free */
            acc += TAB[ns];
        }
    }
    return acc;
}

/* mochi-ct: secret(k) */
static void branch_free_comb(const uint8_t *k, int *acc) {
    for (int w = 0; w < 64; w++) {
        int d = (k[w >> 1] >> ((w & 1) * 4)) & 15;
        acc[0] += d; /* unconditional arithmetic: no branch, no table */
    }
}

/* chained lookup on PUBLIC indices only — both dimensions inspected, clean */
static int public_chain(const uint8_t *sig, const int (*M)[16]) {
    int ns = sig[0] & 15;
    return M[ns][ns & 3];
}

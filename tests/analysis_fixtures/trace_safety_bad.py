"""Seeded regression fixture: every site here must trip jax-trace-safety.
(Checked with the path filter off — fixtures live under tests/.)"""

import numpy as np
import jax
import jax.numpy as jnp


def branch_on_traced(x: jnp.ndarray) -> jnp.ndarray:
    if x > 0:  # Python branch on a traced value
        return x
    return -x


def host_sync(x: jnp.ndarray) -> float:
    return float(x)  # blocking device->host transfer


def item_sync(x: jnp.ndarray):
    return x.item()  # blocking device->host transfer


def numpy_host_op(x: jnp.ndarray):
    return np.sum(x)  # silently drops out of the traced program


@jax.jit
def jitted_unannotated(x):
    while x < 3:  # Python loop on a tracer
        x = x + 1
    return x

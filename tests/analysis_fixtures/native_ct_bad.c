/* Seeded native-const-time violations — three line-distinct sites covering
 * both sub-rules and both ways a name becomes secret (annotation, pattern). */
#include <stdint.h>

/* mochi-ct: secret(k) */
static void annotated_branch(const uint8_t *k, int n, int *out) {
    int d = k[0] & 15;
    if (d) { /* BAD: branch on annotated secret (one level of taint) */
        *out = n;
    }
}

static int named_secret_branch(const uint8_t *priv_key, int n) {
    int acc = 0;
    for (int i = 0; i < n; i++) {
        while (priv_key[i]) { /* BAD: loop condition on pattern-named secret */
            acc++;
        }
    }
    return acc;
}

static int secret_index(const uint8_t *nonce, const int *TAB) {
    int d = nonce[0] & 7;
    return TAB[d]; /* BAD: table lookup indexed by secret-derived value */
}

static int secret_leading_index(const uint8_t *nonce, const int (*COMB)[4]) {
    int d = nonce[0] & 3;
    return COMB[d][0]; /* BAD: secret in the LEADING dimension of a chain */
}

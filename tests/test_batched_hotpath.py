"""Batched hot path: drain→decode→verify→apply pipeline evidence.

The PR-2 acceptance contract, pinned as tests:

* a multi-message drain produces exactly ONE BatchingVerifier round trip
  (envelope auth + every Write2 certificate grant share one bitmap) and
  exactly ONE coalesced socket write for the whole batch's responses;
* a forged envelope inside a batch is rejected (BAD_SIGNATURE) without
  poisoning its batchmates, and a forged GRANT inside one certificate
  drops alone while the surviving quorum still commits;
* the store batch entry points match the single-request entry points
  result-for-result, with per-request failures isolated as values;
* frames arriving on DIFFERENT connections in one scheduling tick drain
  as one batch (the cross-connection axis the round-5 per-socket
  histogram could never see);
* payload dataclasses reject post-construction container mutation (the
  ``_mcode`` encode-cache desync guard, ADVICE r5).
"""

from __future__ import annotations

import asyncio
import struct
import time

import pytest

from mochi_tpu.cluster.config import ClusterConfig
from mochi_tpu.crypto.keys import generate_keypair
from mochi_tpu.net.transport import _RpcServerProtocol, new_msg_id
from mochi_tpu.protocol import (
    Action,
    Envelope,
    FailType,
    Grant,
    MultiGrant,
    Operation,
    RequestFailedFromServer,
    Status,
    Transaction,
    Write1ToServer,
    Write2AnsFromServer,
    Write2ToServer,
    WriteCertificate,
    decode_envelope,
    transaction_hash,
)
from mochi_tpu.server.replica import MochiReplica
from mochi_tpu.server.store import BadRequest, DataStore
from mochi_tpu.verifier.spi import BatchingVerifier

_LEN = struct.Struct(">I")


class _FakeTransport:
    """Counts write() calls and captures bytes; quacks like asyncio.Transport."""

    def __init__(self) -> None:
        self.writes = []
        self._closing = False

    def write(self, data: bytes) -> None:
        self.writes.append(bytes(data))

    def is_closing(self) -> bool:
        return self._closing

    def close(self) -> None:
        self._closing = True

    def abort(self) -> None:
        self._closing = True

    def get_extra_info(self, name, default=None):
        return default

    def pause_reading(self) -> None:
        pass

    def resume_reading(self) -> None:
        pass


def _cluster(n=4):
    kps = {f"server-{i}": generate_keypair() for i in range(n)}
    config = ClusterConfig.build(
        {sid: f"127.0.0.1:{9500 + i}" for i, sid in enumerate(kps)},
        rf=n,
        public_keys={sid: kp.public_key for sid, kp in kps.items()},
    )
    return config, kps


def _signed_write2(config, kps, client_kp, client_id, key, forged_env=False,
                   forged_grant_sid=None):
    txn = Transaction((Operation(Action.WRITE, key, b"v-" + key.encode()),))
    th = transaction_hash(txn)
    grants = {}
    for sid, kp in kps.items():
        mg = MultiGrant(
            {key: Grant(key, 7, config.configstamp, th, Status.OK)}, client_id, sid
        )
        sig = kp.sign(mg.signing_bytes())
        if sid == forged_grant_sid:
            sig = bytes(64)  # forged: fails verification, batchmates must not
        grants[sid] = mg.with_signature(sig)
    env = Envelope(
        payload=Write2ToServer(WriteCertificate(grants), txn),
        msg_id=new_msg_id(),
        sender_id=client_id,
        timestamp_ms=int(time.time() * 1000),
    )
    sig = client_kp.sign(env.signing_bytes())
    if forged_env:
        sig = bytes(64)
    return env.with_signature(sig)


def _frames(*envelopes) -> bytes:
    from mochi_tpu.protocol import encode_envelope

    out = b""
    for env in envelopes:
        frame = encode_envelope(env)
        out += _LEN.pack(len(frame)) + frame
    return out


async def _pump_until(cond, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while not cond():
        assert time.monotonic() < deadline, "condition not reached"
        await asyncio.sleep(0.005)


def _replica_with_counting_verifier(config, kps, client_pub):
    calls = []

    def backend(items):
        from mochi_tpu.crypto.keys import verify

        calls.append(len(items))
        return [verify(it.public_key, it.message, it.signature) for it in items]

    verifier = BatchingVerifier(backend, max_delay_s=0.0)
    replica = MochiReplica(
        "server-0",
        config,
        kps["server-0"],
        verifier=verifier,
        client_public_keys=dict(client_pub),
        shed_lag_ms=0.0,
    )
    return replica, verifier, calls


def test_multi_message_drain_one_roundtrip_one_write():
    """3 signed Write2s in one delivery: 1 verifier round trip (15 items:
    3 envelope sigs + 3x4 grant sigs — own grants defer to the pooled
    round trip for pending-auth envelopes rather than re-signing on the
    loop), 1 coalesced socket write."""

    async def main():
        config, kps = _cluster()
        client_kp = generate_keypair()
        replica, verifier, calls = _replica_with_counting_verifier(
            config, kps, {"client-a": client_kp.public_key}
        )
        proto = _RpcServerProtocol(replica.rpc)
        fake = _FakeTransport()
        proto.connection_made(fake)
        envs = [
            _signed_write2(config, kps, client_kp, "client-a", f"bh-{i}")
            for i in range(3)
        ]
        proto.data_received(_frames(*envs))
        await _pump_until(lambda: len(fake.writes) >= 1)

        assert len(fake.writes) == 1, "responses must leave in ONE write"
        assert verifier.batches_flushed == 1, "ONE BatchingVerifier round trip"
        assert len(calls) == 1 and calls[0] == 15
        # all three committed, responses correlate to their requests
        blob = fake.writes[0]
        responses, pos = [], 0
        while pos < len(blob):
            (length,) = _LEN.unpack_from(blob, pos)
            responses.append(decode_envelope(blob[pos + 4 : pos + 4 + length]))
            pos += 4 + length
        assert len(responses) == 3
        by_reply = {r.reply_to: r for r in responses}
        for env in envs:
            assert isinstance(by_reply[env.msg_id].payload, Write2AnsFromServer)
        for i in range(3):
            sv = replica.store._get(f"bh-{i}")
            assert sv is not None and sv.exists
        await verifier.close()

    asyncio.run(main())


def test_forged_envelope_rejected_without_poisoning_batchmates():
    async def main():
        config, kps = _cluster()
        client_kp = generate_keypair()
        replica, verifier, calls = _replica_with_counting_verifier(
            config, kps, {"client-a": client_kp.public_key}
        )
        proto = _RpcServerProtocol(replica.rpc)
        fake = _FakeTransport()
        proto.connection_made(fake)
        good1 = _signed_write2(config, kps, client_kp, "client-a", "fg-good1")
        forged = _signed_write2(
            config, kps, client_kp, "client-a", "fg-forged", forged_env=True
        )
        good2 = _signed_write2(config, kps, client_kp, "client-a", "fg-good2")
        proto.data_received(_frames(good1, forged, good2))
        await _pump_until(lambda: len(fake.writes) >= 1)

        assert len(fake.writes) == 1 and verifier.batches_flushed == 1
        blob = fake.writes[0]
        responses, pos = [], 0
        while pos < len(blob):
            (length,) = _LEN.unpack_from(blob, pos)
            responses.append(decode_envelope(blob[pos + 4 : pos + 4 + length]))
            pos += 4 + length
        by_reply = {r.reply_to: r for r in responses}
        assert isinstance(by_reply[good1.msg_id].payload, Write2AnsFromServer)
        assert isinstance(by_reply[good2.msg_id].payload, Write2AnsFromServer)
        bad = by_reply[forged.msg_id].payload
        assert isinstance(bad, RequestFailedFromServer)
        assert bad.fail_type == FailType.BAD_SIGNATURE
        # the forged envelope's transaction must NOT have applied
        assert replica.store._get("fg-forged") is None
        assert replica.store._get("fg-good1").exists
        assert replica.store._get("fg-good2").exists
        await verifier.close()

    asyncio.run(main())


def test_forged_grant_drops_alone_quorum_survives():
    """One forged GRANT inside one cert: the grant is dropped, the cert's
    remaining 2f+1 in-set grants still commit, batchmates unaffected."""

    async def main():
        config, kps = _cluster()
        client_kp = generate_keypair()
        replica, verifier, _ = _replica_with_counting_verifier(
            config, kps, {"client-a": client_kp.public_key}
        )
        proto = _RpcServerProtocol(replica.rpc)
        fake = _FakeTransport()
        proto.connection_made(fake)
        # forge server-3's grant (not server-0: its own-grant check is local)
        tainted = _signed_write2(
            config, kps, client_kp, "client-a", "fgr-tainted",
            forged_grant_sid="server-3",
        )
        clean = _signed_write2(config, kps, client_kp, "client-a", "fgr-clean")
        proto.data_received(_frames(tainted, clean))
        await _pump_until(lambda: len(fake.writes) >= 1)

        assert replica.store._get("fgr-tainted").exists  # 3 of 4 grants = quorum
        assert replica.store._get("fgr-clean").exists
        assert replica.metrics.counters.get("replica.dropped-grants") == 1
        await verifier.close()

    asyncio.run(main())


def test_cross_connection_frames_drain_as_one_batch():
    """Two frames on two DIFFERENT connections in one tick: one drain, one
    verifier round trip — the cross-connection aggregation axis."""

    async def main():
        config, kps = _cluster()
        client_kp = generate_keypair()
        replica, verifier, calls = _replica_with_counting_verifier(
            config, kps, {"client-a": client_kp.public_key}
        )
        protos = []
        fakes = []
        for _ in range(2):
            proto = _RpcServerProtocol(replica.rpc)
            fake = _FakeTransport()
            proto.connection_made(fake)
            protos.append(proto)
            fakes.append(fake)
        envs = [
            _signed_write2(config, kps, client_kp, "client-a", f"xc-{i}")
            for i in range(2)
        ]
        # same call stack = same scheduling tick, two distinct connections
        protos[0].data_received(_frames(envs[0]))
        protos[1].data_received(_frames(envs[1]))
        await _pump_until(lambda: all(f.writes for f in fakes))

        assert verifier.batches_flushed == 1, "both connections shared one round trip"
        assert len(calls) == 1
        occupancy = replica.metrics.histograms["replica.batch-occupancy"]
        assert occupancy.total_count == 1 and occupancy.total_sum == 2.0
        drain = replica.metrics.histograms["transport.drain-frames"]
        assert drain.total_count == 1 and drain.total_sum == 2.0
        await verifier.close()

    asyncio.run(main())


def test_optimistic_budget_overflow_uses_second_roundtrip(monkeypatch):
    """Budget exhausted: a pending-auth Write2's certificate waits for the
    auth verdict.  The forged envelope then costs exactly ONE pooled
    verify (its auth item — the pre-batch price); the authentic one still
    commits via the overflow round trip."""
    import mochi_tpu.server.replica as replica_mod

    monkeypatch.setattr(replica_mod, "OPTIMISTIC_CERT_ITEM_BUDGET", 0)

    async def main():
        config, kps = _cluster()
        client_kp = generate_keypair()
        replica, verifier, calls = _replica_with_counting_verifier(
            config, kps, {"client-a": client_kp.public_key}
        )
        proto = _RpcServerProtocol(replica.rpc)
        fake = _FakeTransport()
        proto.connection_made(fake)
        forged = _signed_write2(
            config, kps, client_kp, "client-a", "ob-forged", forged_env=True
        )
        good = _signed_write2(config, kps, client_kp, "client-a", "ob-good")
        proto.data_received(_frames(forged, good))
        await _pump_until(lambda: len(fake.writes) >= 1)

        # round trip 1: the two auth items only; round trip 2: the GOOD
        # envelope's 3 non-own cert grants (forged never reaches it)
        assert calls == [2, 3], calls
        assert replica.store._get("ob-good").exists
        assert replica.store._get("ob-forged") is None
        blob = fake.writes[0] if len(fake.writes) == 1 else b"".join(fake.writes)
        responses, pos = [], 0
        while pos < len(blob):
            (length,) = _LEN.unpack_from(blob, pos)
            responses.append(decode_envelope(blob[pos + 4 : pos + 4 + length]))
            pos += 4 + length
        by_reply = {r.reply_to: r for r in responses}
        assert isinstance(by_reply[good.msg_id].payload, Write2AnsFromServer)
        bad = by_reply[forged.msg_id].payload
        assert isinstance(bad, RequestFailedFromServer)
        assert bad.fail_type == FailType.BAD_SIGNATURE
        await verifier.close()

    asyncio.run(main())


def test_malformed_payload_dies_alone_in_batch():
    """A Write2 whose grant carries type-garbage (string configstamp) blows
    up deep in certificate prep — it must be dropped ALONE (no response,
    like the old per-task blast radius) while its batchmate commits."""

    async def main():
        config, kps = _cluster()
        client_kp = generate_keypair()
        replica, verifier, _ = _replica_with_counting_verifier(
            config, kps, {"client-a": client_kp.public_key}
        )
        proto = _RpcServerProtocol(replica.rpc)
        fake = _FakeTransport()
        proto.connection_made(fake)

        good = _signed_write2(config, kps, client_kp, "client-a", "mp-good")
        # hand-build a cert whose grants carry a STRING configstamp
        txn = Transaction((Operation(Action.WRITE, "mp-bad", b"v"),))
        th = transaction_hash(txn)
        grants = {}
        for sid, kp in kps.items():
            mg = MultiGrant(
                {"mp-bad": Grant("mp-bad", 7, "garbage-cs", th, Status.OK)},
                "client-a",
                sid,
            )
            grants[sid] = mg.with_signature(kp.sign(mg.signing_bytes()))
        bad_env = Envelope(
            payload=Write2ToServer(WriteCertificate(grants), txn),
            msg_id=new_msg_id(),
            sender_id="client-a",
            timestamp_ms=int(time.time() * 1000),
        )
        bad_env = bad_env.with_signature(client_kp.sign(bad_env.signing_bytes()))

        proto.data_received(_frames(bad_env, good))
        await _pump_until(lambda: len(fake.writes) >= 1)
        blob = fake.writes[0]
        responses, pos = [], 0
        while pos < len(blob):
            (length,) = _LEN.unpack_from(blob, pos)
            responses.append(decode_envelope(blob[pos + 4 : pos + 4 + length]))
            pos += 4 + length
        # batchmate answered; the malformed one got NO response at all
        assert [r.reply_to for r in responses] == [good.msg_id]
        assert isinstance(responses[0].payload, Write2AnsFromServer)
        assert replica.store._get("mp-good").exists
        assert replica.store._get("mp-bad") is None
        await verifier.close()

    asyncio.run(main())


def test_macd_admin_write1_denied_on_inline_path():
    """A MAC'd (non-admin-signed) Write1 touching config keys must be
    refused BAD_REQUEST on the grant path — the authorization gate the
    pre-batch dispatch enforced (it must not even acquire grants)."""

    async def main():
        from mochi_tpu.cluster.config import CONFIG_CLUSTER_KEY
        from mochi_tpu.crypto import session as session_crypto

        admin_kp = generate_keypair()
        kps = {f"server-{i}": generate_keypair() for i in range(4)}
        config = ClusterConfig.build(
            {sid: f"127.0.0.1:{9600 + i}" for i, sid in enumerate(kps)},
            rf=4,
            public_keys={sid: kp.public_key for sid, kp in kps.items()},
        )
        config.admin_keys.append(admin_kp.public_key)
        replica = MochiReplica("server-0", config, kps["server-0"], shed_lag_ms=0.0)
        # fake an established MAC session for the client
        session_key = b"k" * 32
        replica._sessions["client-a"] = session_key
        txn = Transaction((Operation(Action.WRITE, CONFIG_CLUSTER_KEY, None),))
        env = Envelope(
            payload=Write1ToServer("client-a", txn, 5, transaction_hash(txn)),
            msg_id=new_msg_id(),
            sender_id="client-a",
            timestamp_ms=int(time.time() * 1000),
        )
        env = session_crypto.seal(env, session_key)
        (response,) = replica.handle_inline_batch([env])
        assert isinstance(response.payload, RequestFailedFromServer)
        assert response.payload.fail_type == FailType.BAD_REQUEST
        # and no grant was issued for the config key
        sv = replica.store._get(CONFIG_CLUSTER_KEY)
        assert sv is None or not sv.grants

    asyncio.run(main())


# ------------------------------------------------------- store batch entries


def test_store_write1_batch_matches_singles_and_isolates_bad_requests():
    config, _ = _cluster()
    store_a = DataStore("server-0", config)
    store_b = DataStore("server-0", config)
    txn = Transaction((Operation(Action.WRITE, "sb-k", None),))
    th = transaction_hash(txn)
    reqs = [
        Write1ToServer("c", txn, 5, th),
        Write1ToServer("c", txn, 2000, th),  # seed out of range -> BadRequest
        Write1ToServer("c", txn, 9, th),
    ]
    batch = store_a.process_write1_batch(reqs)
    assert isinstance(batch[1], BadRequest)
    singles = []
    for req in reqs:
        try:
            singles.append(store_b.process_write1(req))
        except BadRequest as exc:
            singles.append(exc)
    assert batch[0] == singles[0] and batch[2] == singles[2]
    assert str(batch[1]) == str(singles[1])
    # identical grant books afterwards
    assert store_a._get("sb-k").grants == store_b._get("sb-k").grants


def test_store_write2_batch_matches_singles():
    config, kps = _cluster()
    client_kp = generate_keypair()
    envs = [
        _signed_write2(config, kps, client_kp, "c", f"w2b-{i}") for i in range(3)
    ]
    reqs = [e.payload for e in envs]
    store_a = DataStore("server-1", config)
    store_b = DataStore("server-1", config)
    batch = store_a.process_write2_batch(reqs)
    singles = [store_b.process_write2(r) for r in reqs]
    assert batch == singles
    for i in range(3):
        assert store_a._get(f"w2b-{i}").exists


# --------------------------------------------------- frozen payload containers


def test_payload_nested_containers_are_frozen():
    config, kps = _cluster()
    client_kp = generate_keypair()
    env = _signed_write2(config, kps, client_kp, "c", "fz-k")
    wc = env.payload.write_certificate
    mg = next(iter(wc.grants.values()))
    with pytest.raises(TypeError):
        wc.grants["evil"] = mg
    with pytest.raises(TypeError):
        mg.grants["evil"] = next(iter(mg.grants.values()))
    # the decode path (from_obj bypasses __init__) must freeze too
    from mochi_tpu.protocol import encode_envelope

    decoded = decode_envelope(encode_envelope(env))
    dwc = decoded.payload.write_certificate
    with pytest.raises(TypeError):
        dwc.grants["evil"] = mg
    dmg = next(iter(dwc.grants.values()))
    with pytest.raises(TypeError):
        dmg.grants["evil"] = next(iter(dmg.grants.values()))
    # Write1Ok / Write1Refused current_certificates
    store = DataStore("server-0", config)
    txn = Transaction((Operation(Action.WRITE, "fz-w1", None),))
    ok = store.process_write1(
        Write1ToServer("c", txn, 3, transaction_hash(txn))
    )
    with pytest.raises(TypeError):
        ok.current_certificates["evil"] = wc
    # equality with plain-dict-constructed peers is unaffected
    assert wc == WriteCertificate(dict(wc.grants))


def test_frozen_containers_keep_mcode_cache_sound():
    """The exact ADVICE-r5 scenario: encode once (populates the _mcode
    cache), attempt a container mutation, and confirm the encoding cannot
    silently desync — the mutation raises instead."""
    config, kps = _cluster()
    client_kp = generate_keypair()
    env = _signed_write2(config, kps, client_kp, "c", "fz-cache")
    from mochi_tpu.protocol import encode_envelope

    first = encode_envelope(env)  # populates payload.__dict__["_mcode"]
    assert "_mcode" in env.payload.__dict__
    # item assignment raises TypeError; mutating METHODS don't even exist
    # on the proxy (AttributeError) — both shapes block the desync
    with pytest.raises((TypeError, AttributeError)):
        env.payload.write_certificate.grants.clear()
    assert encode_envelope(env) == first


# ----------------------------------------------------------------- histograms


def test_metrics_histogram_snapshot_and_prometheus():
    from mochi_tpu.utils.metrics import Metrics

    m = Metrics()
    h = m.histogram("test.occupancy")
    for v in (1, 1, 3, 17, 5000):
        h.observe(v)
    snap = m.snapshot()["histograms"]["test.occupancy"]
    assert snap["count"] == 5
    assert snap["buckets"]["1"] == 2  # two <=1 observations
    assert snap["buckets"]["+Inf"] == 1  # 5000 overflows the last bound
    text = m.to_prometheus({"server": "s0"})
    assert 'mochi_histogram_bucket{name="test.occupancy",server="s0",le="+Inf"} 5' in text
    assert 'mochi_histogram_count{name="test.occupancy",server="s0"} 5' in text
    # cumulative le buckets are monotonic
    counts = [
        int(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("mochi_histogram_bucket")
    ]
    assert counts == sorted(counts)


# ------------------------------------------------------- standing-rules data


def test_standing_rules_host_record_reads_results_file():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "standing_rules",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "scripts", "standing_rules.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rate, src = mod._host_core_n64_record()
    # the scanner reads the NEWEST committed host battery (ADVICE r5) —
    # r07 as of this round (wheel-less-host record; caveat lives in-file)
    assert src == "benchmarks/results_r07.json"
    assert rate == pytest.approx(1.09)


# ------------------------------------------------- early-quorum safety pins
#
# PR-5 tentpole: the early-quorum predicates are LIVENESS devices — a
# predicate that lies (fires before a real quorum exists) may only slow or
# fail a transaction, never let the client accept a result on fewer than
# 2f+1 verified responses.  Both halves pinned: the Write2 tally and the
# Write1 grant assembly.


def _staggered_sim():
    """Per-replica delays spread far enough apart that each response
    arrives in its own event-loop wake — on bare loopback every reply
    lands in ONE wake and even a lying predicate sees the full set, which
    would void these pins."""
    from mochi_tpu.netsim import LinkEvent, LinkSpec, NetSim

    sim = NetSim.mesh(seed=17, rtt_ms=2.0)
    return sim, [
        LinkEvent(0.0, "set", pat_src, pat_dst, LinkSpec(delay_ms=d / 2.0))
        for i, d in enumerate((4.0, 30.0, 60.0, 90.0))
        for pat_src, pat_dst in ((f"server-{i}", "*"), ("*", f"server-{i}"))
    ]


def test_lying_write2_predicate_cannot_commit_below_quorum(monkeypatch):
    from mochi_tpu.client import txn as txn_mod
    from mochi_tpu.client.errors import InconsistentWrite, RequestRefused
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async def main():
        sim, events = _staggered_sim()
        async with VirtualCluster(4, rf=4, netsim=sim) as vc:  # f=1, quorum=3
            client = vc.client(write_attempts=3, refusal_retries=2)
            await client.execute_write_transaction(
                TransactionBuilder().write("pin-warm", b"w").build()
            )
            for ev in events:
                sim.apply_event(ev)
            # QuorumTally.add lies: "satisfied" at the FIRST response, so
            # every fan-out early-returns with ~1 reply.
            monkeypatch.setattr(
                txn_mod.QuorumTally, "add", lambda self, *a, **k: True
            )
            with pytest.raises((InconsistentWrite, RequestRefused)):
                await client.execute_write_transaction(
                    TransactionBuilder().write("pin-key", b"v").build()
                )

    asyncio.run(asyncio.wait_for(main(), timeout=60))


def test_lying_grant_assembler_cannot_build_thin_certificate(monkeypatch):
    from mochi_tpu.client import txn as txn_mod
    from mochi_tpu.client.errors import RequestRefused
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async def main():
        sim, events = _staggered_sim()
        async with VirtualCluster(4, rf=4, netsim=sim) as vc:
            client = vc.client(write_attempts=3, refusal_retries=2)
            await client.execute_write_transaction(
                TransactionBuilder().write("pin-warm2", b"w").build()
            )
            for ev in events:
                sim.apply_event(ev)
            # GrantAssembler.add lies without recording a chosen subset:
            # Write1 early-returns on the first grant, and the client's
            # authoritative recomputation must refuse to certify.
            monkeypatch.setattr(
                txn_mod.GrantAssembler, "add", lambda self, grant: True
            )
            with pytest.raises(RequestRefused):
                await client.execute_write_transaction(
                    TransactionBuilder().write("pin-key2", b"v").build()
                )

    asyncio.run(asyncio.wait_for(main(), timeout=60))


def test_early_quorum_kill_switch_disables_predicates():
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client(early_quorum=False)
            await client.execute_write_transaction(
                TransactionBuilder().write("ks", b"v").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("ks").build()
            )
            assert res.operations[0].value == b"v"
            # no predicate ever installed: the early-return counter and
            # straggler families must be absent
            assert "fanout.early-return" not in client.metrics.counters
            assert not any(
                n.startswith("fanout") for n in client.metrics.histograms
            )

    asyncio.run(asyncio.wait_for(main(), timeout=60))

"""Codec round-trip + canonicality tests."""

import pytest

from mochi_tpu.protocol.codec import decode, encode


CASES = [
    None,
    True,
    False,
    0,
    1,
    127,
    128,
    2**40,
    -1,
    -2**40,
    b"",
    b"\x00\xff" * 10,
    "",
    "hello é世界",
    [],
    [1, "two", b"three", None, [4, [5]]],
    {},
    {"b": 1, "a": [2, {"z": None}], "c": b"x"},
]


@pytest.mark.parametrize("value", CASES, ids=range(len(CASES)))
def test_roundtrip(value):
    assert decode(encode(value)) == value


def test_dict_key_order_canonical():
    assert encode({"a": 1, "b": 2}) == encode({"b": 2, "a": 1})


def test_tuple_encodes_as_list():
    assert decode(encode((1, 2))) == [1, 2]


def test_trailing_bytes_rejected():
    with pytest.raises(ValueError):
        decode(encode(1) + b"\x00")


def test_truncated_rejected():
    data = encode([1, "abc", b"xyz"])
    for cut in range(1, len(data)):
        with pytest.raises(ValueError):
            decode(data[:cut])


def test_non_str_dict_key_rejected():
    with pytest.raises(TypeError):
        encode({1: "x"})


def test_unknown_type_rejected():
    with pytest.raises(TypeError):
        encode(1.5)


def test_deep_nesting_guard():
    value = []
    for _ in range(100):
        value = [value]
    with pytest.raises(ValueError):
        encode(value)

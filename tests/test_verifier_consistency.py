"""Regressions from review: CPU/TPU verdict parity + batcher chunk safety.

For BFT safety every replica must reach the SAME verdict on the same bytes
regardless of verify backend; divergence lets an adversary split honest
replicas' quorums (review finding on non-canonical encodings).
"""

import numpy as np

from mochi_tpu.crypto import batch_verify, keys
from mochi_tpu.verifier.spi import VerifyItem

P = (1 << 255) - 19
L = (1 << 252) + 27742317777372353535851937790883648493


def test_non_canonical_pubkey_rejected_on_both_paths():
    # Non-canonical identity encoding: y = p+1 ≡ 1 (the identity point),
    # R = identity, S = 0 satisfies OpenSSL's decode-mod-p check but MUST be
    # rejected identically everywhere.
    pub = (P + 1).to_bytes(32, "little")
    sig = (1).to_bytes(32, "little") + (0).to_bytes(32, "little")
    msg = b"split-brain attempt"
    assert keys.verify(pub, msg, sig) is False
    assert batch_verify.verify_batch([VerifyItem(pub, msg, sig)]) == [False]


def test_non_canonical_r_and_s_rejected_on_both_paths():
    kp = keys.generate_keypair()
    msg = b"hello"
    sig = bytearray(kp.sign(msg))
    # S >= L
    bad_s = sig[:32] + (L).to_bytes(32, "little")
    assert keys.verify(kp.public_key, msg, bytes(bad_s)) is False
    assert batch_verify.verify_batch([VerifyItem(kp.public_key, msg, bytes(bad_s))]) == [False]
    # R with y >= p
    bad_r = (P + 3).to_bytes(32, "little") + sig[32:]
    assert keys.verify(kp.public_key, msg, bytes(bad_r)) is False
    assert batch_verify.verify_batch([VerifyItem(kp.public_key, msg, bytes(bad_r))]) == [False]


def test_valid_signatures_still_pass_both_paths():
    kp = keys.generate_keypair()
    msg = b"canonical"
    sig = kp.sign(msg)
    assert keys.verify(kp.public_key, msg, sig) is True
    assert batch_verify.verify_batch([VerifyItem(kp.public_key, msg, sig)]) == [True]


def test_backend_chunks_use_only_ready_buckets(monkeypatch):
    """A batch whose own bucket isn't compiled must be served only through
    already-ready program shapes (no synchronous compile on the serving path)."""
    backend = batch_verify.JaxBatchBackend(min_device_items=0)  # force the device path: these tests pin bucket/chunk behavior
    backend._ready = {16, 128}
    # mark bucket 64 as already compiling so no background warmup thread is
    # spawned — we only want to observe the serving path's launches
    backend._compiling = {64}

    used_buckets = []
    real = batch_verify.verify_batch

    def spy(items, device=None, bucket=None):
        used_buckets.append(bucket if bucket is not None else batch_verify._bucket_size(len(items)))
        return real(items, device=device, bucket=bucket)

    monkeypatch.setattr(batch_verify, "verify_batch", spy)
    kp = keys.generate_keypair()
    msg = b"chunk"
    items = [VerifyItem(kp.public_key, msg, kp.sign(msg))] * 40
    out = backend(items)
    assert list(out) == [True] * 40
    # bucket(40)=64 is not ready: every launched shape must be in {16, 128}
    assert used_buckets and all(b in (16, 128) for b in used_buckets)


def test_failed_bucket_not_rescheduled():
    backend = batch_verify.JaxBatchBackend(min_device_items=0)  # force the device path: these tests pin bucket/chunk behavior
    backend._ready = {16}
    backend._failed = {64}
    kp = keys.generate_keypair()
    msg = b"x"
    items = [VerifyItem(kp.public_key, msg, kp.sign(msg))] * 40
    out = backend(items)
    assert list(out) == [True] * 40
    assert 64 not in backend._compiling


def test_small_batches_take_the_cpu_crossover(monkeypatch):
    """Below min_device_items the backend must verify on OpenSSL without
    touching the device path (a device launch costs a fixed round trip
    that would poison commit latency for thin traffic)."""
    calls = []
    real = batch_verify.verify_batch

    def spy(items, device=None, bucket=None):
        calls.append(len(items))
        return real(items, device=device, bucket=bucket)

    monkeypatch.setattr(batch_verify, "verify_batch", spy)
    backend = batch_verify.JaxBatchBackend(min_device_items=64)
    kp2 = keys.generate_keypair()
    items = [VerifyItem(kp2.public_key, b"c%d" % i, kp2.sign(b"c%d" % i)) for i in range(20)]
    bad = bytearray(items[4].signature)
    bad[1] ^= 1
    items[4] = VerifyItem(items[4].public_key, items[4].message, bytes(bad))
    out = backend(items)
    assert list(out) == [i != 4 for i in range(20)]
    assert not calls, "device path was used below the crossover"
    # at/above the threshold the device path engages
    big = [VerifyItem(kp2.public_key, b"d%d" % i, kp2.sign(b"d%d" % i)) for i in range(64)]
    assert all(backend(big))
    assert calls, "device path not used at the crossover"

"""Known-answer tests for the pure-Python host crypto fallback.

The fallback must be *bit-compatible* with OpenSSL (deterministic RFC 8032
signing; identical cofactorless verify verdicts): a mixed cluster — some
nodes with the ``cryptography`` wheel, some on the fallback — must agree on
every signature, or BFT quorums split on honest traffic.  The RFC vectors
pin that compatibility without needing OpenSSL installed.
"""

import pytest

from mochi_tpu.crypto import hostfallback as hf
from mochi_tpu.crypto import keys

# RFC 8032 §7.1 TEST 1-3: (seed, public, message, signature)
RFC8032_VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


@pytest.mark.parametrize("seed_h,pub_h,msg_h,sig_h", RFC8032_VECTORS)
def test_rfc8032_sign_and_verify(seed_h, pub_h, msg_h, sig_h):
    seed = bytes.fromhex(seed_h)
    pub = bytes.fromhex(pub_h)
    msg = bytes.fromhex(msg_h)
    sig = bytes.fromhex(sig_h)
    assert hf.public_from_seed(seed) == pub
    assert hf.sign(seed, msg) == sig
    assert hf.verify(pub, msg, sig)
    assert not hf.verify(pub, msg + b"x", sig)
    tampered = bytearray(sig)
    tampered[0] ^= 1
    assert not hf.verify(pub, msg, bytes(tampered))


def test_rfc7748_diffie_hellman_vector():
    # RFC 7748 §6.1
    a = bytes.fromhex(
        "77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a"
    )
    b = bytes.fromhex(
        "5dab087e624a8a4b79e17f8b83800ee66f3bb1292618b6fd1c2f8b27ff88e0eb"
    )
    a_pub = bytes.fromhex(
        "8520f0098930a754748b7ddcb43ef75a0dbf3a0d26381af4eba4a98eaa9b4e6a"
    )
    b_pub = bytes.fromhex(
        "de9edb7d7b7dc1b4d35b61c2ece435373f8343c85b78674dadfc7e146f882b4f"
    )
    shared = bytes.fromhex(
        "4a5d9d5ba4ce2de1728e3bf480350f25e07e21c947d19e3376f09b3c1e161742"
    )
    assert hf.x25519_public(a) == a_pub
    assert hf.x25519_public(b) == b_pub
    assert hf.x25519(a, b_pub) == shared
    assert hf.x25519(b, a_pub) == shared


def test_x25519_rejects_small_order_peer():
    with pytest.raises(ValueError):
        hf.x25519(b"\x42" * 32, b"\x00" * 32)


def test_wrong_length_key_material_rejected():
    # Contract parity with OpenSSL: cryptography raises ValueError on
    # non-32-byte keys, and a mixed cluster must reject the same malformed
    # handshake/seed bytes on both backends rather than silently masking.
    with pytest.raises(ValueError):
        hf.x25519(b"\x42" * 31, b"\x17" * 32)
    with pytest.raises(ValueError):
        hf.x25519(b"\x42" * 32, b"\x17" * 33)
    with pytest.raises(ValueError):
        hf.public_from_seed(b"\x01" * 31)
    with pytest.raises(ValueError):
        hf.sign(b"\x01" * 33, b"msg")


def test_keys_module_roundtrip_whatever_backend():
    # keys.* must work identically whether OpenSSL is installed or not —
    # this asserts the public surface, not the backend.
    kp = keys.generate_keypair()
    sig = kp.sign(b"quorum evidence")
    assert len(sig) == 64 and len(kp.public_key) == 32
    assert keys.verify(kp.public_key, b"quorum evidence", sig)
    assert not keys.verify(kp.public_key, b"forged evidence", sig)
    # determinism (RFC 8032): the replica own-grant compare depends on it
    assert kp.sign(b"quorum evidence") == sig
    # derived keypair agrees
    kp2 = keys.keypair_from_seed(kp.private_seed)
    assert kp2.public_key == kp.public_key


def test_fallback_agrees_with_host_library_if_present():
    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey,
        )
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            NoEncryption,
            PrivateFormat,
            PublicFormat,
        )
    except ImportError:
        pytest.skip("cryptography not installed; differential check skipped")
    priv = Ed25519PrivateKey.generate()
    seed = priv.private_bytes(Encoding.Raw, PrivateFormat.Raw, NoEncryption())
    pub = priv.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    msg = b"differential"
    assert hf.public_from_seed(seed) == pub
    assert hf.sign(seed, msg) == priv.sign(msg)
    assert hf.verify(pub, msg, priv.sign(msg))


def test_session_handshake_on_current_backend():
    from mochi_tpu.crypto import session

    h1 = session.new_handshake()
    h2 = session.new_handshake()
    k1 = session.derive_key(h1, h2.public_bytes, h2.nonce, "c", "s", True)
    k2 = session.derive_key(h2, h1.public_bytes, h1.nonce, "c", "s", False)
    assert k1 == k2 and len(k1) == 32

"""Durable storage engine (round 14, ``mochi_tpu/storage``): WAL framing
under torn/bit-flipped tails, verified crash recovery, tamper conviction,
the crash-between-snapshot-and-truncate window, delta anti-entropy, and the
cross-process SIGKILL -> restart -> zero-acked-write-loss contract.

The torn-write tests are exhaustive over offsets: a segment is truncated
(and separately bit-flipped) at EVERY byte offset / record boundary and the
scan must stop cleanly at the last fully valid record — never a partial
apply, never a resynchronization past garbage (lengths after a bad frame
cannot be trusted).

The tamper tests are the Byzantine-restart story: an adversary who rewrites
its own log recomputes CRCs trivially, so framing is NOT the integrity
argument — replay re-verifies every certificate's grant signatures through
the batch path and validates through the Write2 rules, and each tampered
entry is convicted with attribution (mutated value, forged grant signature,
reordered records), surfaced through ``InvariantChecker`` invariant 5.
"""

from __future__ import annotations

import asyncio
import os
import shutil
import tempfile

from mochi_tpu.client.txn import TransactionBuilder
from mochi_tpu.protocol import SyncEntry
from mochi_tpu.storage import wal
from mochi_tpu.storage.durable import frame_snapshot, unframe_snapshot
from mochi_tpu.testing.invariants import InvariantChecker
from mochi_tpu.testing.process_cluster import ProcessCluster
from mochi_tpu.testing.virtual_cluster import VirtualCluster

SID = "server-0"


def _build_segment(path: str, records, server_id: str = SID, index: int = 1):
    w = wal.SegmentWriter(path, server_id, index)
    for seq, rtype, body in records:
        w.append(wal.encode_record(seq, rtype, body))
    w.close()


def _sample_records(n: int = 5):
    # varying body sizes so record boundaries land at irregular offsets
    return [
        (i + 1, wal.RT_COMMIT, [[f"k{i}"], [[1, f"k{i}", b"x" * (7 * i)]], {}])
        for i in range(n)
    ]


# ------------------------------------------------------------- WAL framing


def test_wal_roundtrip(tmp_path):
    path = str(tmp_path / wal.segment_name(1))
    _build_segment(path, _sample_records())
    with open(path, "rb") as fh:
        scan = wal.scan_segment(fh.read(), SID)
    assert not scan.torn
    assert [r.seq for r in scan.records] == [1, 2, 3, 4, 5]
    assert scan.records[2].body[0] == ["k2"]


def test_foreign_segment_rejected(tmp_path):
    path = str(tmp_path / wal.segment_name(1))
    _build_segment(path, _sample_records(1), server_id="server-9")
    with open(path, "rb") as fh:
        data = fh.read()
    try:
        wal.scan_segment(data, SID)
    except ValueError as exc:
        assert "server-9" in str(exc)
    else:
        raise AssertionError("foreign segment replayed silently")


def test_torn_tail_every_offset(tmp_path):
    """Truncate the segment at EVERY byte offset: the scan must return
    exactly the records fully contained in the prefix, flag ``torn`` for
    any cut that is not a clean record boundary, and never yield a
    partial record."""
    path = str(tmp_path / wal.segment_name(1))
    _build_segment(path, _sample_records())
    with open(path, "rb") as fh:
        data = fh.read()
    hdr_end = wal.read_segment_header(data, SID)
    full = wal.scan_segment(data, SID)
    starts = [r.offset for r in full.records]
    ends = starts[1:] + [len(data)]
    clean_cuts = {hdr_end, *ends}
    for cut in range(hdr_end, len(data) + 1):
        scan = wal.scan_segment(data[:cut], SID)
        expect = [r.seq for r, end in zip(full.records, ends) if end <= cut]
        assert [r.seq for r in scan.records] == expect, f"cut={cut}"
        assert scan.torn == (cut not in clean_cuts), f"cut={cut}"
        if scan.torn:
            assert scan.detail, f"cut={cut}: torn scans must say why"


def test_bitflip_at_every_record_boundary(tmp_path):
    """Flip one bit at each record's frame start (and at a byte inside
    each payload): recovery stops cleanly BEFORE the damaged record —
    the records after it are unreachable by design (their offsets derive
    from a length that can no longer be trusted)."""
    path = str(tmp_path / wal.segment_name(1))
    _build_segment(path, _sample_records())
    with open(path, "rb") as fh:
        data = fh.read()
    full = wal.scan_segment(data, SID)
    for i, rec in enumerate(full.records):
        for delta in (0, 4, 8):  # length field, crc field, payload
            pos = rec.offset + delta
            flipped = bytearray(data)
            flipped[pos] ^= 0x40
            scan = wal.scan_segment(bytes(flipped), SID)
            got = [r.seq for r in scan.records]
            want = [r.seq for r in full.records[:i]]
            assert got == want, f"record {i} +{delta}: {got} != {want}"
            assert scan.torn, f"record {i} +{delta}: damage not flagged"


def test_snapshot_frame_crc():
    blob = b"snapshot-doc-bytes" * 10
    framed = frame_snapshot(blob)
    assert unframe_snapshot(framed) == blob
    for pos in (0, len(framed) // 2, len(framed) - 1):
        damaged = bytearray(framed)
        damaged[pos] ^= 0x01
        try:
            unframe_snapshot(bytes(damaged))
        except ValueError:
            continue
        raise AssertionError(f"corrupt snapshot (byte {pos}) accepted")


# ------------------------------------------- cluster-level recovery/tamper


async def _populated(td: str, n: int = 12):
    vc = VirtualCluster(4, rf=4, storage_dir=td)
    await vc.start()
    client = vc.client()
    for i in range(n):
        await client.execute_write_transaction(
            TransactionBuilder().write(f"sk{i}", b"v%d" % i).build()
        )
    return vc, client


def _freeze_storage(td: str, server_id: str) -> str:
    """Copy a replica's live storage dir aside — the disk image of a crash
    at this instant (the graceful restart that follows would otherwise
    snapshot + truncate it)."""
    src = os.path.join(td, server_id)
    dst = src + ".crash"
    shutil.copytree(src, dst)
    return dst


def _restore_storage(td: str, server_id: str, frozen: str) -> None:
    dst = os.path.join(td, server_id)
    shutil.rmtree(dst)
    shutil.move(frozen, dst)


def _rewrite_last_segment(directory: str, server_id: str, mutate) -> None:
    """Adversarial log rewrite: decode the newest segment's records, apply
    ``mutate(records)`` (records are mutable ``[seq, rtype, body]``
    triples), re-frame with CORRECT CRCs (an adversary recomputes them
    trivially) and write the file back."""
    index, path = wal.list_segments(directory)[-1]
    with open(path, "rb") as fh:
        data = fh.read()
    start = wal.read_segment_header(data, server_id)
    scan = wal.scan_segment(data, server_id)
    assert not scan.torn
    records = [[r.seq, r.rtype, r.body] for r in scan.records]
    mutate(records)
    with open(path, "wb") as fh:
        fh.write(
            data[:start]
            + b"".join(wal.encode_record(s, t, b) for s, t, b in records)
        )


def _last_data_commit(records):
    for rec in reversed(records):
        if rec[1] == wal.RT_COMMIT and rec[2][0][0].startswith("sk"):
            return rec
    raise AssertionError("no data commit found in segment")


def test_recover_from_disk_and_delta_resync():
    """Restart from disk: committed state replays (verified, zero
    convictions), and the follow-up resync ships only the DELTA written
    while the replica was down — shard digests match for untouched state,
    the gap keys move as delta pulls, and nothing moves as a full pull."""

    async def body(td):
        vc, client = await _populated(td, n=16)
        try:
            gap_keys = [f"gap{i}" for i in range(4)]

            async def commit_gap(_sid):
                # the victim is down here: a 3/4 quorum commits the gap
                for k in gap_keys:
                    await client.execute_write_transaction(
                        TransactionBuilder().write(k, b"late").build()
                    )

            fresh = await vc.restart_replica(
                "server-1", resync=True, before_boot=commit_gap
            )
            report = fresh.storage.replay_report()
            assert report["convicted"] == 0, report
            assert report["entries"] >= 16
            for i in range(16):
                sv = fresh.store._get(f"sk{i}")
                assert sv is not None and sv.value == b"v%d" % i, f"sk{i}"
            # the gap arrived by resync — and arrived as a DELTA
            for k in gap_keys:
                sv = fresh.store._get(k)
                assert sv is not None and sv.value == b"late", k
            ae = fresh.storage_stats()["anti_entropy"]
            assert ae["shards_matched"] > 0, ae
            assert 0 < ae["delta_keys_pulled"] <= 3 * (len(gap_keys) + 2), ae
            assert ae["full_keys_pulled"] == 0, ae
        finally:
            await vc.close()

    with tempfile.TemporaryDirectory() as td:
        asyncio.run(asyncio.wait_for(body(td), timeout=120))


def test_tampered_wal_value_convicted():
    """Byzantine restart, leg 1: a certificate's transaction value mutated
    in the log.  The grants still verify — but they signed the ORIGINAL
    transaction hash, so verified replay refuses the entry, convicts with
    attribution, and the tampered value is never served."""

    async def body(td):
        vc, _client = await _populated(td)
        try:
            victim = vc.replica("server-1")
            await victim.storage.flush()
            frozen = _freeze_storage(td, "server-1")
            tampered_key = []

            def mutate(records):
                rec = _last_data_commit(records)
                tampered_key.append(rec[2][0][0])
                rec[2][1][0][2] = b"EVIL"  # body[1] = txn ops; op[2] = value

            _rewrite_last_segment(frozen, "server-1", mutate)

            fresh = await vc.restart_replica(
                "server-1",
                before_boot=lambda sid: _restore_storage(td, sid, frozen),
            )
            report = fresh.storage.replay_report()
            assert report["convicted"] >= 1, report
            assert any(
                c["key"] == tampered_key[0] for c in report["convictions"]
            ), report
            sv = fresh.store._get(tampered_key[0])
            assert sv is None or sv.value != b"EVIL"
            # invariant 5 surfaces the conviction as evidence, not violation
            checker = InvariantChecker([fresh])
            checker.check_now()
            rep = checker.report()
            assert rep["storage_replay_convictions"] >= 1, rep
            assert rep["ok"], rep["violations"]
        finally:
            await vc.close()

    with tempfile.TemporaryDirectory() as td:
        asyncio.run(asyncio.wait_for(body(td), timeout=120))


def test_tampered_wal_forged_grant_sigs_convicted():
    """Byzantine restart, leg 2: every grant signature of a logged
    certificate forged.  The batch re-verification fails them all, the
    entry is refused outright, and serving the convicted transaction
    anyway would trip invariant 5."""

    async def body(td):
        vc, _client = await _populated(td)
        try:
            victim = vc.replica("server-1")
            await victim.storage.flush()
            frozen = _freeze_storage(td, "server-1")
            tampered_key = []

            def mutate(records):
                rec = _last_data_commit(records)
                tampered_key.append(rec[2][0][0])
                for mg_obj in rec[2][2].values():  # cert: {sid: mg_obj}
                    mg_obj[3] = b"\x00" * 64  # MultiGrant signature slot

            _rewrite_last_segment(frozen, "server-1", mutate)

            fresh = await vc.restart_replica(
                "server-1",
                before_boot=lambda sid: _restore_storage(td, sid, frozen),
            )
            report = fresh.storage.replay_report()
            assert any(
                "signature" in c["reason"] for c in report["convictions"]
            ), report
        finally:
            await vc.close()

    with tempfile.TemporaryDirectory() as td:
        asyncio.run(asyncio.wait_for(body(td), timeout=120))


def test_tampered_wal_reordered_records_convicted():
    """Byzantine restart, leg 3: two log records swapped (an epoch/commit
    reorder).  Sequence numbers are covered by the framing, so the replay
    convicts the regression instead of adopting history out of order."""

    async def body(td):
        vc, _client = await _populated(td)
        try:
            victim = vc.replica("server-1")
            await victim.storage.flush()
            frozen = _freeze_storage(td, "server-1")

            def mutate(records):
                assert len(records) >= 2
                records[-1], records[-2] = records[-2], records[-1]

            _rewrite_last_segment(frozen, "server-1", mutate)

            fresh = await vc.restart_replica(
                "server-1",
                before_boot=lambda sid: _restore_storage(td, sid, frozen),
            )
            report = fresh.storage.replay_report()
            assert any(
                "regression" in c["reason"] for c in report["convictions"]
            ), report
        finally:
            await vc.close()

    with tempfile.TemporaryDirectory() as td:
        asyncio.run(asyncio.wait_for(body(td), timeout=120))


def test_torn_nonfinal_segment_convicted():
    """An honest crash tears only the FINAL segment (later segments exist
    only after a clean rotation) — a torn non-final segment is evidence of
    a rewritten log and must be convicted, not absorbed."""

    async def body(td):
        vc, _client = await _populated(td)
        try:
            victim = vc.replica("server-1")
            await victim.storage.flush()
            frozen = _freeze_storage(td, "server-1")
            index, path = wal.list_segments(frozen)[-1]
            with open(path, "r+b") as fh:
                fh.truncate(os.path.getsize(path) - 3)  # tear its tail
            # a later, cleanly-rotated segment makes the torn one non-final
            _build_segment(
                os.path.join(frozen, wal.segment_name(index + 1)),
                [(10_000, wal.RT_RECLAIM, ["zz", 1, b"", 1])],
                server_id="server-1",
                index=index + 1,
            )

            fresh = await vc.restart_replica(
                "server-1",
                before_boot=lambda sid: _restore_storage(td, sid, frozen),
            )
            report = fresh.storage.replay_report()
            assert any(
                "torn non-final" in c["reason"] for c in report["convictions"]
            ), report
        finally:
            await vc.close()

    with tempfile.TemporaryDirectory() as td:
        asyncio.run(asyncio.wait_for(body(td), timeout=120))


def test_crash_between_snapshot_and_truncate():
    """Regression for the snapshot crash window: the snapshot (with its
    WAL watermark) is durable BEFORE any segment is deleted, so a crash
    in between leaves (new snapshot + superfluous log prefix).  Recovery
    must replay the snapshot, skip every covered record via the
    watermark, and convict nothing — the overlap is a no-op, not a
    duplicate."""

    async def body(td):
        vc, _client = await _populated(td)
        try:
            victim = vc.replica("server-1")
            await victim.storage.flush()
            frozen = _freeze_storage(td, "server-1")  # full pre-snapshot WAL
            await victim.storage.snapshot(victim.store)
            # crash state: the NEW snapshot landed, the old segments never
            # got deleted
            shutil.copy(
                os.path.join(td, "server-1", "snapshot.bin"),
                os.path.join(frozen, "snapshot.bin"),
            )

            fresh = await vc.restart_replica(
                "server-1",
                before_boot=lambda sid: _restore_storage(td, sid, frozen),
            )
            report = fresh.storage.replay_report()
            assert report["convicted"] == 0, report
            for i in range(12):
                sv = fresh.store._get(f"sk{i}")
                assert sv is not None and sv.value == b"v%d" % i, f"sk{i}"
        finally:
            await vc.close()

    with tempfile.TemporaryDirectory() as td:
        asyncio.run(asyncio.wait_for(body(td), timeout=120))


def test_torn_segment_header_is_torn_not_fatal(tmp_path):
    """A crash DURING segment creation leaves a 0-byte (or partial-header)
    final segment — the honest shape when ``open`` raced the header hitting
    disk.  The scan must fold it into the torn result (clean stop, zero
    records), never raise and brick the boot; a DECODABLE header naming
    another server stays a hard error (restore mix-up)."""
    path = str(tmp_path / wal.segment_name(1))
    _build_segment(path, _sample_records(2))
    with open(path, "rb") as fh:
        data = fh.read()
    hdr_end = wal.read_segment_header(data, SID)
    for cut in range(hdr_end):  # every header truncation incl. empty file
        scan = wal.scan_segment(data[:cut], SID)
        assert scan.torn and not scan.records, f"cut={cut}"
    # foreign-but-intact headers must still refuse loudly, not scan torn
    try:
        wal.scan_segment(data, "server-9")
    except wal.TornSegmentHeader:
        raise AssertionError("restore mix-up downgraded to a torn header")
    except ValueError:
        pass


def test_truncated_final_segment_recovers():
    """Cluster arc for the torn segment header: SIGKILL during rotation
    leaves an empty final segment on disk; the replica must boot, flag the
    torn tail, and serve every committed key — not die in recover()."""

    async def body(td):
        vc, _client = await _populated(td)
        try:
            victim = vc.replica("server-1")
            await victim.storage.flush()
            frozen = _freeze_storage(td, "server-1")
            index = wal.list_segments(frozen)[-1][0]
            # crash shape: the next segment's file exists, header never
            # reached disk
            open(os.path.join(frozen, wal.segment_name(index + 1)), "wb").close()

            fresh = await vc.restart_replica(
                "server-1",
                before_boot=lambda sid: _restore_storage(td, sid, frozen),
            )
            report = fresh.storage.replay_report()
            assert report["torn_tail"] is True, report
            assert report["convicted"] == 0, report
            for i in range(12):
                sv = fresh.store._get(f"sk{i}")
                assert sv is not None and sv.value == b"v%d" % i, f"sk{i}"
        finally:
            await vc.close()

    with tempfile.TemporaryDirectory() as td:
        asyncio.run(asyncio.wait_for(body(td), timeout=120))


def test_snapshot_captures_under_append_lock():
    """Regression for the snapshot watermark race: a flush queued on the
    append lock may drain records staged after the snapshot's own flush
    into the PRE-rotation segment.  The blob + watermark must therefore be
    captured while HOLDING the lock, atomically with the rotation —
    captured outside it, the truncation deletes a segment holding acked
    records above the snapshot's coverage (silent acked-write loss)."""

    async def body(td):
        from unittest import mock

        from mochi_tpu.server import persistence

        vc, _client = await _populated(td, n=4)
        try:
            victim = vc.replica("server-1")
            engine = victim.storage
            real = persistence.snapshot_bytes
            lock_held_at_capture = []

            def spy(store, extra=None):
                lock_held_at_capture.append(engine._append_lock.locked())
                return real(store, extra=extra)

            with mock.patch.object(persistence, "snapshot_bytes", spy):
                await engine.snapshot(victim.store)
            assert lock_held_at_capture == [True], (
                "snapshot blob/watermark captured outside the append lock: "
                "a contending flush can strand acked records in the "
                "about-to-be-truncated segment"
            )
        finally:
            await vc.close()

    with tempfile.TemporaryDirectory() as td:
        asyncio.run(asyncio.wait_for(body(td), timeout=120))


def test_idempotent_reapply_not_restaged():
    """Regression: an equal-ts re-apply of the SAME transaction (a client
    Write2 retry, a resync pull of an already-current key) is an
    idempotent no-op and must NOT stage a duplicate WAL record — the next
    recovery would convict the duplicate as tampering, an honest replica
    manufacturing Byzantine evidence about itself."""

    async def body(td):
        vc, _client = await _populated(td)
        try:
            victim = vc.replica("server-1")
            sv = victim.store._get("sk3")
            entry = SyncEntry("sk3", sv.last_transaction, sv.current_certificate)
            before = victim.storage.wal_entries
            assert victim.store.apply_sync_entry(entry) is False
            assert victim.storage.wal_entries == before, (
                "idempotent re-apply staged a duplicate commit record"
            )
            # the full arc: a resync (which re-pulls current keys, config
            # keyspace twice per peer) followed by a SECOND restart that
            # replays whatever the resync staged — zero convictions
            await vc.restart_replica("server-1", resync=True)
            fresh = await vc.restart_replica("server-1")
            report = fresh.storage.replay_report()
            assert report["convicted"] == 0, report
            for i in range(12):
                sv = fresh.store._get(f"sk{i}")
                assert sv is not None and sv.value == b"v%d" % i, f"sk{i}"
        finally:
            await vc.close()

    with tempfile.TemporaryDirectory() as td:
        asyncio.run(asyncio.wait_for(body(td), timeout=120))


# ------------------------------------------------------- analysis hygiene


def test_storage_package_analysis_clean():
    """Satellite pin: the full static pass (async-blocking — all file IO
    executor-wrapped — await-races over the WAL writer's shared-state
    awaits, cancellation hygiene, const-time) over ``mochi_tpu/storage``
    reports zero findings AND the package carries zero suppression
    comments: the engine is clean outright, not clean-by-waiver."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "mochi_tpu.analysis", "mochi_tpu/storage"],
        cwd=repo, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 new" in proc.stdout and "0 suppressed" in proc.stdout, proc.stdout
    for name in ("wal.py", "spi.py", "durable.py", "paged.py", "__init__.py"):
        with open(os.path.join(repo, "mochi_tpu", "storage", name)) as fh:
            assert "mochi-lint" not in fh.read(), f"suppression in {name}"


# --------------------------------------- cross-process SIGKILL -> recover


def test_sigkill_full_cluster_zero_acked_write_loss():
    """The acceptance pin: ProcessCluster under live load, EVERY replica
    SIGKILLed mid-stream (no drain, no snapshot — the only durability is
    the flush-before-ack WAL write), all four restarted from disk, and
    every acknowledged write must read back — zero lost."""

    async def body():
        async with ProcessCluster(
            4, rf=4, n_processes=4, storage_dir=True, wal_fsync="group"
        ) as pc:
            client = pc.client(timeout_s=8.0)
            acked = {}

            async def load():
                i = 0
                while True:
                    key, value = f"pk{i}", b"v%d" % i
                    try:
                        await client.execute_write_transaction(
                            TransactionBuilder().write(key, value).build()
                        )
                    except Exception:
                        return  # in-flight at the kill: indeterminate
                    acked[key] = value
                    i += 1

            writer = asyncio.ensure_future(load())
            while len(acked) < 10:
                await asyncio.sleep(0.02)
            for i in range(4):
                pc.kill_replica(f"server-{i}")
            await writer  # errors out on the dead cluster
            await client.close()

            for i in range(4):
                await pc.restart_replica(f"server-{i}")
            reader = pc.client(timeout_s=8.0)
            lost = []
            for key, value in sorted(acked.items()):
                res = await reader.execute_read_transaction(
                    TransactionBuilder().read(key).build()
                )
                if res.operations[0].value != value:
                    lost.append(key)
            assert not lost, f"{len(lost)} acked writes lost: {lost[:5]}"
            pc.check_alive()

    asyncio.run(asyncio.wait_for(body(), timeout=240))

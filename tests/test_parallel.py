"""Sharded verify + quorum tally over an 8-device virtual CPU mesh.

Exercises the multi-chip path of BASELINE.json config 5 the way the driver's
``dryrun_multichip`` does: real ``jax.sharding.Mesh``, ``shard_map``, and a
cross-device ``psum`` for the 2f+1 tally.
"""

import numpy as np
import pytest

import jax

from mochi_tpu.crypto.batch_verify import prepare
from mochi_tpu.crypto.keys import keypair_from_seed
from mochi_tpu.parallel import (
    make_mesh,
    make_quorum_step,
    make_sharded_verify,
    pad_to_multiple,
)
from mochi_tpu.verifier.spi import VerifyItem


def _signed_items(n, forge=()):
    items = []
    for i in range(n):
        kp = keypair_from_seed(bytes([(i + 7) % 251] * 32))
        msg = b"parallel test %d" % i
        sig = bytearray(kp.sign(msg))
        if i in forge:
            sig[0] ^= 0xFF
        items.append(VerifyItem(kp.public_key, msg, bytes(sig)))
    return items


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual devices"
    return make_mesh(8)


@pytest.mark.slow
def test_sharded_verify_matches_expected(mesh):
    items = _signed_items(16, forge={3, 10})
    y_a, sign_a, y_r, sign_r, s_bits, h_bits, pre_ok = prepare(items)
    assert pre_ok.all()
    verify = make_sharded_verify(mesh)
    bitmap = np.asarray(verify(y_a, sign_a, y_r, sign_r, s_bits, h_bits))
    expect = np.ones(16, dtype=bool)
    expect[[3, 10]] = False
    assert (bitmap == expect).all()


@pytest.mark.slow
def test_quorum_step_tally_and_commit(mesh):
    # 4 quorum slots x 4 votes each; forge one vote in slot 1 and three in
    # slot 2 -> with threshold 3 slots {0,1,3} commit, slot 2 does not.
    n, n_groups = 16, 4
    group_ids = (np.arange(n, dtype=np.int32) % n_groups).astype(np.int32)
    # slot = i % 4: forging items 1 (slot 1), 2, 6, 10 (slot 2)
    items = _signed_items(n, forge={1, 2, 6, 10})
    tensors = prepare(items)[:6]
    step = make_quorum_step(mesh, n_groups)
    bitmap, counts, committed = (
        np.asarray(x) for x in step(*tensors, group_ids, np.int32(3))
    )
    assert (counts == np.array([4, 3, 1, 4])).all()
    assert (committed == np.array([True, True, False, True])).all()
    assert bitmap.sum() == 12


@pytest.mark.slow
def test_pad_to_multiple_dead_groups(mesh):
    n, n_groups = 10, 3
    items = _signed_items(n)
    tensors = prepare(items)[:6]
    group_ids = (np.arange(n, dtype=np.int32) % n_groups).astype(np.int32)
    arrays, m = pad_to_multiple(tuple(tensors) + (group_ids,), n, 8, dead_group=n_groups)
    assert m == 16
    step = make_quorum_step(mesh, n_groups + 1)
    bitmap, counts, committed = (
        np.asarray(x) for x in step(*arrays[:6], arrays[6], np.int32(4))
    )
    # padded lanes must all fail verification and tally only into the dead slot
    assert bitmap[:n].all() and not bitmap[n:].any()
    assert (counts[:n_groups] == np.bincount(group_ids, minlength=n_groups)).all()
    assert counts[n_groups] == 0


@pytest.mark.slow
def test_sharded_backend_all_rejected_skips_device(mesh):
    """ShardedJaxBatchBackend: a garbage-flood chunk (every precheck fails)
    returns all-False without dispatching the mesh program, and without
    bumping the dispatch counter that gates bucket-readiness (mirrors the
    single-device fast path; round-4 review finding)."""
    from mochi_tpu.crypto import batch_verify
    from mochi_tpu.verifier.tpu import ShardedJaxBatchBackend

    backend = ShardedJaxBatchBackend(mesh=mesh, min_device_items=0)
    good = _signed_items(8)
    garbage = [
        VerifyItem(it.public_key, it.message, it.signature[:32] + b"\xff" * 32)
        for it in good
    ]
    before = batch_verify.device_dispatch_count()
    assert backend._sharded_verify(garbage) == [False] * 8
    assert batch_verify.device_dispatch_count() == before
    # mixed batch still runs the mesh program with per-item verdicts
    out = backend._sharded_verify(good + garbage)
    assert out == [True] * 8 + [False] * 8
    assert batch_verify.device_dispatch_count() == before + 1

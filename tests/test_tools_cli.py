"""CLI-level tests for the operator tools (gen_cluster, reconfigure).

These are the entry points a human operator actually types (the verify
recipe uses them verbatim); everything below them is covered elsewhere —
this pins the argument parsing, file formats and exit behavior.
"""

from __future__ import annotations

import asyncio

from mochi_tpu.cluster.config import ClusterConfig
from mochi_tpu.client.txn import TransactionBuilder
from mochi_tpu.crypto.keys import keypair_from_seed
from mochi_tpu.testing.virtual_cluster import VirtualCluster
from mochi_tpu.tools import gen_cluster, reconfigure


def run(coro):
    asyncio.run(coro)


def test_gen_cluster_cli_produces_loadable_config(tmp_path):
    out = tmp_path / "cluster"
    gen_cluster.main(
        [
            "--out-dir", str(out),
            "--servers", "5",
            "--rf", "4",
            "--base-port", "19301",
            "--with-admin",
        ]
    )
    cfg = ClusterConfig.from_json((out / "cluster_config.json").read_text())
    assert cfg.n_servers == 5 and cfg.rf == 4 and cfg.quorum == 3
    assert cfg.admin_keys, "--with-admin must pin an admin key"
    # every seed file reconstructs the keypair whose public key the
    # config carries
    for sid in cfg.servers:
        seed = bytes.fromhex((out / f"{sid}.seed").read_text().strip())
        kp = keypair_from_seed(seed)
        assert cfg.public_keys[sid] == kp.public_key, sid
    admin_seed = bytes.fromhex((out / "admin.seed").read_text().strip())
    assert keypair_from_seed(admin_seed).public_key in cfg.admin_keys


def test_reconfigure_cli_removes_server_live(tmp_path):
    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("cli-key", b"v").build()
            )
            cfg_path = tmp_path / "cfg.json"
            cfg_path.write_text(vc.config.to_json())
            out_path = tmp_path / "cfg2.json"
            # reconfigure.main runs its own event loop — give it a thread
            await asyncio.to_thread(
                reconfigure.main,
                [
                    "--config", str(cfg_path),
                    "--remove", "server-4",
                    "--out", str(out_path),
                ],
            )
            new_cfg = ClusterConfig.from_json(out_path.read_text())
            assert "server-4" not in new_cfg.servers
            assert new_cfg.configstamp == vc.config.configstamp + 1
            # the cluster actually installed it and still serves the data
            for r in vc.replicas[:4]:
                assert r.config.configstamp == new_cfg.configstamp
            res = await client.execute_read_transaction(
                TransactionBuilder().read("cli-key").build()
            )
            assert res.operations[0].value == b"v"
            await client.close()

    run(main())


def test_publish_guards_protect_scoreboard():
    """run_all's published-block merge: errored runs and CPU fallbacks must
    never clobber good / live-TPU entries (the round-4 incident)."""
    from benchmarks.run_all import merge_published

    baseline = {
        "published": {
            "1": {"metric": "m1", "value": 500.0, "platform": "cpu"},
            "2": {"metric": "m2", "value": 91000.0, "platform": "tpu"},
        }
    }
    results = [
        {"config": "1", "metric": "m1", "error": "timeout"},          # guard 1
        {"config": "2", "metric": "m2", "value": 300.0, "platform": "cpu"},  # guard 2
        {"config": "3", "metric": "m3", "value": 42.0, "platform": "cpu"},   # fresh
        {"config": "2b", "metric": "m2", "value": 95000.0, "platform": "tpu"},
    ]
    skipped = merge_published(baseline, results, "99")
    pub = baseline["published"]
    assert pub["1"]["value"] == 500.0 and "error" not in pub["1"]
    assert pub["2"]["value"] == 91000.0 and pub["2"]["platform"] == "tpu"
    assert pub["3"]["value"] == 42.0
    assert pub["3"]["source"] == "benchmarks/results_r99.json"
    # a fresh TPU run publishes normally
    assert pub["2b"]["value"] == 95000.0
    assert len(skipped) == 2

    # an errored run with NO existing entry still records (loud, not silent)
    merge_published(baseline, [{"config": "7", "metric": "m7", "error": "x"}], "99")
    assert baseline["published"]["7"]["error"] == "x"

"""Comb-first routing: the known-signer engine as the DEFAULT verify path.

The comb kernel itself is covered differentially by ``tests/test_comb.py``;
these tests pin the PR-3 promotion of that kernel to the default engine:

* ``register_signers`` plumbing — the one call a replica makes at boot and
  on reconfig must reach the device registry / host fallback through any
  SPI composition (Caching/Coalescing/Batching wrappers);
* the replica actually makes that call, at boot and on reconfiguration;
* mixed batches through the ROUTED SPI path (registry hits on the comb
  program, misses on the ladder, one merged bitmap) stay bit-for-bit equal
  to the host verifier — including forged signatures and unknown signers,
  which must fail alone without dragging batchmates down;
* the router's occupancy counters actually count.
"""

from __future__ import annotations

import asyncio

import pytest

from mochi_tpu.crypto import batch_verify, keys
from mochi_tpu.crypto.batch_verify import JaxBatchBackend
from mochi_tpu.verifier.spi import (
    BatchingVerifier,
    CachingVerifier,
    CoalescingVerifier,
    CpuVerifier,
    SignatureVerifier,
    VerifyItem,
    verifier_stats,
)


@pytest.fixture(scope="module")
def signers():
    return [keys.keypair_from_seed(bytes([i + 11] * 32)) for i in range(4)]


# ------------------------------------------------------------- registration


def test_register_signers_walks_spi_composition(signers):
    backend = JaxBatchBackend()
    v = CachingVerifier(CoalescingVerifier(BatchingVerifier(backend)))
    assert v.register_signers([kp.public_key for kp in signers]) is True
    assert backend.registry is not None
    assert len(backend.registry) == len(signers)
    # idempotent: a reconfig re-registering the full set must not grow it
    assert v.register_signers([kp.public_key for kp in signers]) is True
    assert len(backend.registry) == len(signers)
    st = verifier_stats(CachingVerifier(BatchingVerifier(backend)))
    assert st["inner"]["comb"]["registered_signers"] == len(signers)


def test_cpu_verifier_registration_primes_host_fallback(signers):
    from mochi_tpu.crypto import keys as keys_mod

    routed = CpuVerifier().register_signers([kp.public_key for kp in signers])
    if keys_mod.host_crypto_engine() != "pure-python":
        # OpenSSL AND the native-C engine (round 9) keep no per-signer
        # state — registration reports unrouted so callers don't credit a
        # warmup that doesn't exist.
        assert routed is False
    else:
        from mochi_tpu.crypto import hostfallback

        assert routed is True
        for kp in signers:
            assert (
                hostfallback._seen_signers.get(kp.public_key, 0)
                >= hostfallback._TABLE_PROMOTE_AFTER
            )


class _RecordingVerifier(SignatureVerifier):
    def __init__(self):
        self.registered: list = []

    def register_signers(self, pubs):
        self.registered.append(list(pubs))
        return True

    async def verify_batch(self, items):
        return [
            keys.verify(it.public_key, it.message, it.signature) for it in items
        ]


def test_replica_registers_config_signers_at_boot_and_reconfig(signers):
    from mochi_tpu.cluster.config import ClusterConfig
    from mochi_tpu.server.replica import MochiReplica

    async def drive():
        sids = [f"server-{i}" for i in range(4)]
        cfg = ClusterConfig.build(
            {sid: "127.0.0.1:1" for sid in sids},
            rf=4,
            public_keys={sid: kp.public_key for sid, kp in zip(sids, signers)},
        )
        verifier = _RecordingVerifier()
        replica = MochiReplica(
            "server-0", cfg, signers[0], verifier=verifier, port=0
        )
        await replica.start()
        try:
            assert verifier.registered, "boot did not register config signers"
            assert set(verifier.registered[0]) == {
                kp.public_key for kp in signers
            }
            # live reconfiguration re-registers the FULL new membership
            extra = keys.keypair_from_seed(bytes([99] * 32))
            new_cfg = cfg.evolve(
                {**{sid: "127.0.0.1:1" for sid in sids}, "server-4": "127.0.0.1:1"},
                public_keys={"server-4": extra.public_key},
            )
            replica._install_config(new_cfg.to_json().encode())
            assert set(verifier.registered[-1]) == {
                kp.public_key for kp in signers
            } | {extra.public_key}
        finally:
            await replica.close()

    asyncio.run(drive())


# ------------------------------------------------------- routed mixed batch


def test_routed_mixed_batch_differential_vs_host(signers):
    """Forged-signature and unknown-signer items through the ROUTED
    BatchingVerifier path: registry hits ride the comb program, misses the
    ladder, one merged bitmap — bit-for-bit the host verifier's verdicts
    (OpenSSL when installed, else the pure-Python fallback)."""
    backend = JaxBatchBackend(min_device_items=0)
    v = BatchingVerifier(backend, max_delay_s=0.0)
    assert v.register_signers([kp.public_key for kp in signers])
    backend.warmup([16])  # compiles ladder AND comb at bucket 16

    unknown = keys.keypair_from_seed(bytes([77] * 32))
    items = []
    for i, kp in enumerate(signers):
        msg = b"routed %d" % i
        items.append(VerifyItem(kp.public_key, msg, kp.sign(msg)))
    # registered signer, forged signature (fails alone on the comb leg)
    items.append(
        VerifyItem(signers[0].public_key, b"forged", signers[0].sign(b"other"))
    )
    # unknown signer, valid signature (rides the ladder leg, passes)
    items.append(VerifyItem(unknown.public_key, b"u", unknown.sign(b"u")))
    # unknown signer, forged signature (ladder leg, fails alone)
    items.append(VerifyItem(unknown.public_key, b"u2", unknown.sign(b"xx")))
    # malformed: rejected at host precheck on either leg
    items.append(VerifyItem(b"\x00" * 31, b"m", b"\x00" * 64))
    # registered signer, signature by a DIFFERENT registered key
    items.append(
        VerifyItem(signers[1].public_key, b"swap", signers[2].sign(b"swap"))
    )

    before = batch_verify.comb_routing_counts()
    bitmap = asyncio.run(v.verify_batch(items))
    asyncio.run(v.close())
    expected = [
        keys.verify(it.public_key, it.message, it.signature) for it in items
    ]
    assert bitmap == expected, (bitmap, expected)
    # sanity on the workload itself: real passes AND real failures occurred
    assert any(bitmap) and not all(bitmap)

    after = batch_verify.comb_routing_counts()
    # registered items (4 valid + forged + wrong-key = 6) routed comb;
    # 2 unknown + 1 malformed routed ladder; one mixed merged round trip
    assert after["comb_items"] - before["comb_items"] == 6
    assert after["ladder_items"] - before["ladder_items"] == 3
    assert after["mixed_batches"] - before["mixed_batches"] == 1


def test_routed_all_known_batch_uses_comb_only(signers):
    backend = JaxBatchBackend(min_device_items=0)
    backend.register_signers([kp.public_key for kp in signers])
    backend.warmup([16])
    items = [
        VerifyItem(kp.public_key, b"all-known", kp.sign(b"all-known"))
        for kp in signers
    ]
    before = batch_verify.comb_routing_counts()
    bitmap = backend(items)
    after = batch_verify.comb_routing_counts()
    assert list(bitmap) == [True] * len(signers)
    assert after["comb_items"] - before["comb_items"] == len(signers)
    assert after["ladder_items"] == before["ladder_items"]
    assert after["mixed_batches"] == before["mixed_batches"]

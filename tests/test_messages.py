"""Message schema round-trips, envelope encoding, transaction hashing.

Mirrors the reference's hash tests (``UtilsTest.java:11-33``: identical
transactions hash equal, different ones differ).
"""

from mochi_tpu.protocol import (
    Action,
    Envelope,
    FailType,
    Grant,
    HelloFromServer,
    HelloToServer,
    MultiGrant,
    Operation,
    OperationResult,
    ReadFromServer,
    ReadToServer,
    RequestFailedFromServer,
    Status,
    Transaction,
    TransactionResult,
    Write1OkFromServer,
    Write1RefusedFromServer,
    Write1ToServer,
    Write2AnsFromServer,
    Write2ToServer,
    WriteCertificate,
    decode_envelope,
    encode_envelope,
    transaction_hash,
)


def sample_txn() -> Transaction:
    return Transaction(
        (
            Operation(Action.WRITE, "k1", b"v1"),
            Operation(Action.READ, "k2"),
            Operation(Action.DELETE, "k3"),
        )
    )


def sample_multigrant(signed: bool = False) -> MultiGrant:
    txh = transaction_hash(sample_txn())
    mg = MultiGrant(
        grants={
            "k1": Grant("k1", 1042, 1, txh, Status.OK),
            "k3": Grant("k3", 1042, 1, txh, Status.OK),
        },
        client_id="client-abc",
        server_id="server-1",
    )
    if signed:
        mg = mg.with_signature(b"\x01" * 64)
    return mg


def sample_certificate() -> WriteCertificate:
    return WriteCertificate(
        {f"server-{i}": sample_multigrant(signed=True) for i in range(3)}
    )


PAYLOADS = [
    ReadToServer("client-1", sample_txn(), "nonce-1"),
    ReadFromServer(
        TransactionResult(
            (
                OperationResult(b"v", sample_certificate(), True, Status.OK),
                OperationResult(None, None, False, Status.WRONG_SHARD),
            )
        ),
        "nonce-1",
        "rid-1",
    ),
    Write1ToServer("client-1", sample_txn(), 517, transaction_hash(sample_txn())),
    Write1OkFromServer(sample_multigrant(signed=True), {"k1": sample_certificate()}),
    Write1RefusedFromServer(sample_multigrant(), {"k1": sample_certificate()}, "client-1"),
    Write2ToServer(sample_certificate(), sample_txn()),
    Write2AnsFromServer(TransactionResult((OperationResult(b"v"),)), "rid-2"),
    RequestFailedFromServer(FailType.BAD_SIGNATURE, "forged"),
    HelloToServer("hi"),
    HelloFromServer("hi back"),
]


def test_envelope_roundtrip_all_payload_types():
    for payload in PAYLOADS:
        env = Envelope(
            payload,
            msg_id="msg-123",
            sender_id="client-1",
            reply_to="msg-122",
            timestamp_ms=1712345678901,
            signature=b"\x02" * 64,
        )
        decoded = decode_envelope(encode_envelope(env))
        assert decoded == env, type(payload).__name__


def test_transaction_hash_stable_and_distinct():
    t1, t2 = sample_txn(), sample_txn()
    assert transaction_hash(t1) == transaction_hash(t2)
    assert len(transaction_hash(t1)) == 64
    t3 = Transaction((Operation(Action.WRITE, "k1", b"DIFFERENT"),))
    assert transaction_hash(t1) != transaction_hash(t3)


def test_signing_bytes_exclude_signature():
    mg = sample_multigrant()
    assert mg.signing_bytes() == mg.with_signature(b"\x05" * 64).signing_bytes()
    env = Envelope(HelloToServer(), "m1", "s1")
    assert env.signing_bytes() == env.with_signature(b"\x06" * 64).signing_bytes()


def test_signing_bytes_cover_content():
    mg = sample_multigrant()
    mutated = MultiGrant(
        grants={**mg.grants, "k9": Grant("k9", 7, 1, b"\x00" * 64, Status.OK)},
        client_id=mg.client_id,
        server_id=mg.server_id,
    )
    assert mg.signing_bytes() != mutated.signing_bytes()


def test_six_bytes_splice_is_byte_identical():
    """The payload-level mcode cache (round 5) splices cached payload bytes
    between a freshly encoded tag and tail; the result must be byte-equal
    to encoding the whole 6-element list in one call, for EVERY payload
    type — this is what keeps fan-out envelopes (shared payload, distinct
    msg_id/MAC) wire-compatible with round-4 peers."""
    from mochi_tpu.protocol.codec import encode
    from mochi_tpu.protocol.messages import _TAG_BY_TYPE

    for payload in PAYLOADS:
        env = Envelope(payload, "msg-1", "sender-1", "reply-1", 1712345678901)
        reference = encode(
            [
                _TAG_BY_TYPE[type(payload)],
                payload.to_obj(),
                env.msg_id,
                env.sender_id,
                env.reply_to,
                env.timestamp_ms,
            ]
        )
        assert env._six_bytes == reference, type(payload).__name__
        # second envelope over the SAME payload object hits the cache and
        # must produce its own correct bytes (different msg_id)
        env2 = Envelope(payload, "msg-2", "sender-1", "reply-1", 1712345678901)
        assert "_mcode" in payload.__dict__
        reference2 = reference.replace(b"msg-1", b"msg-2")
        assert env2._six_bytes == reference2, type(payload).__name__
        decoded = decode_envelope(encode_envelope(env2))
        assert decoded.payload == payload, type(payload).__name__

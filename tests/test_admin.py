"""Admin HTTP shell: status/metrics/json endpoints over a live replica."""

import asyncio
import json
import urllib.request

from mochi_tpu.admin import AdminServer
from mochi_tpu.client.txn import TransactionBuilder
from mochi_tpu.testing.virtual_cluster import VirtualCluster


def _get(port: int, path: str):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
        body = resp.read().decode()
        return resp.status, resp.headers.get("Content-Type"), body


def test_admin_endpoints():
    asyncio.run(asyncio.wait_for(_main(), timeout=60))


async def _main():
    async with VirtualCluster(5, rf=4) as vc:
        client = vc.client()
        await client.execute_write_transaction(
            TransactionBuilder().write("adm-key", b"v").build()
        )
        replica = vc.replicas[0]
        admin = AdminServer(replica, port=0)
        await admin.start()
        try:
            port = admin.bound_port
            loop = asyncio.get_running_loop()

            status, ctype, body = await loop.run_in_executor(None, _get, port, "/status")
            assert status == 200 and ctype == "application/json"
            doc = json.loads(body)
            assert doc["server_id"] == replica.server_id
            assert doc["cluster"]["rf"] == 4 and doc["cluster"]["quorum"] == 3
            assert doc["store"]["keys"] >= 0
            # per-shard ownership/traffic accounting (token-ring): with
            # rf=4 of 5 servers each replica serves 4/5 of the ring, and
            # the committed write above must have counted as OWNED traffic
            # on an owning replica — foreign counters stay 0 when client
            # routing matches the ring
            shard = doc["shard"]
            assert shard["tokens_primary"] > 0
            assert 0 < shard["tokens_in_replica_set"] <= 1024
            if replica.server_id in replica.config.replica_set_for_key("adm-key"):
                assert shard["write1_owned"] >= 1 and shard["write2_applied"] >= 1
            assert shard["write1_foreign"] == 0 and shard["read_foreign"] == 0
            # admission-control surface (docs/OPERATIONS.md §4g): the
            # deterministic load signal, shed state, and bounded-table
            # sizes — admission defaults ON, nothing shed at this load
            ov = doc["overload"]
            assert ov["enabled"] is True and ov["shed_p"] == 0.0
            assert ov["overloaded"] is False and ov["write1_shed"] == 0
            assert ov["sessions"]["size"] >= 1  # the client's MAC session
            assert ov["sessions"]["size"] <= ov["sessions"]["max"]
            assert ov["sessions"]["evictions"] == 0
            for k in ("load", "batch_ewma", "inflight_envs",
                      "sendq_out_bytes", "sendq_total_bytes",
                      "paused_conns", "verify_inflight", "retry_after_ms"):
                assert k in ov, k
            # per-client grant/quota/reclaim surface (round 13, docs
            # §4h): knobs + wedge liveness metric + per-identity ledger
            cl = doc["clients"]
            for k in ("quota", "ttl_ms", "reclaims", "quota_refused",
                      "outstanding_total", "max_wedge_ms", "open_wedges",
                      "per_client", "quota_refusals_served"):
                assert k in cl, k
            assert cl["reclaims"] == 0 and cl["quota_refused"] == 0
            if replica.server_id in replica.config.replica_set_for_key("adm-key"):
                me = cl["per_client"].get(client.client_id, {})
                assert me.get("issued", 0) >= 1, cl["per_client"]
            # durable-storage surface (round 14, docs §4i): the key is
            # present in EVERY posture — the in-memory default reports
            # engine "memory" with zeroed anti-entropy accounting
            st = doc["storage"]
            assert st["engine"] == "memory"
            assert st["anti_entropy"]["delta_keys_pulled"] == 0
            assert st["anti_entropy"]["full_keys_pulled"] == 0

            status, _, body = await loop.run_in_executor(None, _get, port, "/metrics")
            assert status == 200
            json.loads(body)

            status, ctype, body = await loop.run_in_executor(
                None, _get, port, "/metrics.prom"
            )
            assert status == 200 and "text/plain" in ctype
            assert "mochi_counter_total{" in body or "mochi_timer_count{" in body
            assert f'server="{replica.server_id}"' in body
            # the overload gauges ride one stat-labeled family
            assert 'mochi_shed{stat="shed_p"' in body
            assert 'mochi_shed{stat="sendq_out_bytes"' in body
            assert 'mochi_shed{stat="sessions.size"' in body
            # per-client grant accounting: aggregate rows (client="") plus
            # one row per tracked identity
            assert 'mochi_client{client="",stat="reclaims"' in body
            assert 'mochi_client{client="",stat="quota"' in body
            # storage gauges ride one stat-labeled family in every posture
            assert 'mochi_storage{stat="anti_entropy.delta_keys_pulled"' in body
            if replica.server_id in replica.config.replica_set_for_key("adm-key"):
                assert f'mochi_client{{client="{client.client_id}"' in body
            # every sample line: name{labels} value
            for line in body.splitlines():
                if line and not line.startswith("#"):
                    assert "} " in line and line.startswith("mochi_"), line

            # round-15 causal tracing: the span-ring posture on /status and
            # the Chrome trace-event export at /trace (empty ring without
            # MOCHI_TRACE*, but the surface must exist and parse)
            tr = doc["trace"]
            for k in ("enabled", "sample_rate", "ring", "spans_recorded"):
                assert k in tr, tr
            status, ctype, body = await loop.run_in_executor(
                None, _get, port, "/trace"
            )
            assert status == 200 and ctype == "application/json"
            trace_doc = json.loads(body)
            assert isinstance(trace_doc["traceEvents"], list)
            assert trace_doc["otherData"]["process"] == f"replica:{replica.server_id}"

            status, _, body = await loop.run_in_executor(None, _get, port, "/json")
            assert status == 200 and json.loads(body)["hello"] == "mochi-tpu"

            status, ctype, body = await loop.run_in_executor(None, _get, port, "/")
            assert status == 200 and "text/html" in ctype and replica.server_id in body
            # human-readable cluster view (L6 parity with the reference's
            # static index.html): membership table with every member's URL,
            # live store + verifier sections
            for other in replica.config.servers.values():
                assert other.server_id in body and other.url in body
            assert "Membership" in body and "Store" in body and "Verifier" in body
            assert "Overload" in body and "shed_p" in body
            # the round-13 Clients table: quota knobs + wedge metric rows
            assert "Clients" in body and "max_wedge_ms" in body
            # the round-14 Storage table: engine posture row at minimum
            assert "Storage" in body and "engine" in body
        finally:
            await admin.close()


def test_admin_storage_surfaces_durable():
    """Round-14 satellite pin: a durable-engine replica's /status "storage"
    key, the ``mochi_storage{stat=...}`` prom family (WAL growth, fsync
    count, snapshot age, replay progress, anti-entropy deltas), the fsync
    latency histogram, and the "/" page Storage table."""

    async def body(td):
        async with VirtualCluster(4, rf=4, storage_dir=td) as vc:
            client = vc.client()
            for i in range(8):
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"adm-st-{i}", b"v").build()
                )
            replica = vc.replicas[0]
            # a deterministic snapshot so snapshot_age_s/seq are live
            await replica.storage.snapshot(replica.store)
            admin = AdminServer(replica, port=0)
            await admin.start()
            try:
                port = admin.bound_port
                loop = asyncio.get_running_loop()
                _, _, raw = await loop.run_in_executor(None, _get, port, "/status")
                st = json.loads(raw)["storage"]
                assert st["engine"] == "durable"
                assert st["fsync"] == "always"
                assert st["wal_entries"] >= 8
                assert st["wal_bytes"] > 0
                assert st["fsyncs"] >= 1
                assert st["snapshots"] >= 1
                assert st["snapshot_age_s"] is not None
                assert st["replay"]["convicted"] == 0
                assert "anti_entropy" in st
                _, _, prom = await loop.run_in_executor(
                    None, _get, port, "/metrics.prom"
                )
                for stat in (
                    "wal_entries", "wal_bytes", "fsyncs", "snapshots",
                    "snapshot_age_s", "replay.entries", "replay.convicted",
                    "anti_entropy.delta_keys_pulled",
                ):
                    assert f'mochi_storage{{stat="{stat}"' in prom, stat
                # the fsync latency histogram rides the registry exposition
                # ('always' policy: the ack path itself fsync'd above)
                assert 'name="storage-fsync-ms"' in prom
                _, _, page = await loop.run_in_executor(None, _get, port, "/")
                assert "Storage" in page and "wal_entries" in page
            finally:
                await admin.close()

    import os
    import tempfile

    # 'always' so the ack path itself fsyncs: the fsync counter and latency
    # histogram are then deterministically non-empty (the default 'group'
    # policy fsyncs on a timer — a race in a test)
    os.environ["MOCHI_WAL_FSYNC"] = "always"
    try:
        with tempfile.TemporaryDirectory() as td:
            asyncio.run(asyncio.wait_for(body(td), timeout=120))
    finally:
        del os.environ["MOCHI_WAL_FSYNC"]


def test_admin_storage_surfaces_paged():
    """Round-17 satellite pin: a paged-engine replica's /status "storage"
    key (pages/cache/compaction/memtable blocks), the flattened
    ``mochi_storage{stat="pages.resident"}``-style prom leaves, and the
    "/" page Storage table rendering the paged counters."""

    async def body(td):
        async with VirtualCluster(
            4, rf=4, storage_dir=td, storage_engine="paged"
        ) as vc:
            client = vc.client()
            for i in range(8):
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"adm-pg-{i}", b"v").build()
                )
            replica = vc.replicas[0]
            # a deterministic page flush so pages/cache counters are live
            await replica.storage.flush()
            await replica.storage.snapshot(replica.store)
            admin = AdminServer(replica, port=0)
            await admin.start()
            try:
                port = admin.bound_port
                loop = asyncio.get_running_loop()
                _, _, raw = await loop.run_in_executor(None, _get, port, "/status")
                st = json.loads(raw)["storage"]
                assert st["engine"] == "paged"
                assert st["pages"]["count"] >= 1
                assert st["pages"]["resident"] >= 1
                assert st["pages"]["convicted"] == 0
                assert st["cache"]["cap_bytes"] > 0
                assert st["cache"]["resident_bytes"] >= 0
                assert st["compaction"]["debt"] >= 0
                assert st["memtable"]["cap_bytes"] > 0
                _, _, prom = await loop.run_in_executor(
                    None, _get, port, "/metrics.prom"
                )
                for stat in (
                    "pages.count", "pages.resident", "pages.convicted",
                    "cache.cap_bytes", "cache.hits", "cache.misses",
                    "cache.evictions", "compaction.debt",
                    "compaction.runs", "memtable.dirty_keys",
                ):
                    assert f'mochi_storage{{stat="{stat}"' in prom, stat
                _, _, page = await loop.run_in_executor(None, _get, port, "/")
                assert "Storage" in page and "pages.count" in page
                assert "cache.cap_bytes" in page
            finally:
                await admin.close()

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        asyncio.run(asyncio.wait_for(body(td), timeout=120))


def test_fanout_surfaces_and_client_admin_shell():
    asyncio.run(asyncio.wait_for(_fanout_main(), timeout=60))


async def _fanout_main():
    from mochi_tpu.admin import ClientAdminServer
    from mochi_tpu.utils.metrics import STRAGGLER_BOUNDS_MS

    async with VirtualCluster(4, rf=4) as vc:
        client = vc.client()
        await client.execute_write_transaction(
            TransactionBuilder().write("fanout-key", b"v").build()
        )
        loop = asyncio.get_running_loop()

        # replica /status always carries the fanout key (empty peers on a
        # pure responder — dashboards need no existence probe)
        admin = AdminServer(vc.replicas[0], port=0)
        await admin.start()
        try:
            _, _, body = await loop.run_in_executor(
                None, _get, admin.bound_port, "/status"
            )
            doc = json.loads(body)
            assert doc["fanout"] == {"early_returns": 0, "peers": {}}
        finally:
            await admin.close()

        # the client shell surfaces the INITIATOR-side evidence: populate
        # the exact names transport's straggler drain records
        m = client.metrics
        m.mark("fanout.early-return")
        m.mark("fanout.late-response.server-2")
        m.mark("fanout.straggler-timeout.server-3")
        m.histogram("fanout-straggler-ms.server-2", STRAGGLER_BOUNDS_MS).observe(3.1)
        cadmin = ClientAdminServer(client, port=0)
        await cadmin.start()
        try:
            port = cadmin.bound_port
            _, ctype, body = await loop.run_in_executor(None, _get, port, "/status")
            assert ctype == "application/json"
            doc = json.loads(body)
            assert doc["client_id"] == client.client_id
            assert doc["fanout"]["early_returns"] == 1
            peers = doc["fanout"]["peers"]
            assert peers["server-2"]["late_response"] == 1
            assert peers["server-2"]["straggler_ms"]["count"] == 1
            assert peers["server-3"]["straggler_timeout"] == 1

            _, ctype, body = await loop.run_in_executor(
                None, _get, port, "/metrics.prom"
            )
            assert ctype.startswith("text/plain")
            assert 'mochi_fanout{peer="server-2",stat="late_response"' in body
            assert 'stat="early_returns"' in body
            # the full lateness histogram rides the standard family
            assert 'name="fanout-straggler-ms.server-2"' in body

            _, ctype, body = await loop.run_in_executor(None, _get, port, "/")
            assert ctype == "text/html"
            assert "Fan-out" in body and "server-2" in body
            # the client shell's own grant/quota view (round 13): the
            # Clients table plus its /status "clients" key
            assert "Clients" in body and "quota_refusals" in body
            _, _, body = await loop.run_in_executor(None, _get, port, "/status")
            doc = json.loads(body)
            assert doc["clients"]["quota_refusals"] == 0
            assert "per_replica_quota_refused" in doc["clients"]
            # round-15: the client shell exports its span ring too
            assert "trace" in doc and "sample_rate" in doc["trace"]
            _, ctype, body = await loop.run_in_executor(None, _get, port, "/trace")
            assert ctype == "application/json"
            assert isinstance(json.loads(body)["traceEvents"], list)
        finally:
            await cadmin.close()

        # replica "/" page gained the Fan-out table too
        admin2 = AdminServer(vc.replicas[1], port=0)
        await admin2.start()
        try:
            _, _, body = await loop.run_in_executor(
                None, _get, admin2.bound_port, "/"
            )
            assert "Fan-out" in body
        finally:
            await admin2.close()

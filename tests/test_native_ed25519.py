"""Differential suite for the native-C Ed25519 engine (native/hbatch.c
verify_batch/sign_prepared) vs the pure-Python reference engine
(crypto/hostfallback) and, when the wheel is installed, OpenSSL.

BFT safety rides on every node reaching the SAME verdict on the same
bytes, so the contract under test is *agreement*, not just "valid
signatures verify": forgeries, non-canonical encodings, low-order points
and oversized scalars must produce identical verdicts from every engine a
mixed cluster might run.
"""

from __future__ import annotations

import hashlib
import os
import random

import pytest

from mochi_tpu.crypto import hostfallback as hf
from mochi_tpu.crypto import keys
from mochi_tpu.native import get_hbatch

hb = get_hbatch()
pytestmark = pytest.mark.skipif(
    hb is None or not hasattr(hb, "verify_batch"),
    reason="no native toolchain / engine",
)

try:  # optional third engine: OpenSSL via the cryptography wheel
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PublicKey

    def openssl_verdict(pub: bytes, msg: bytes, sig: bytes):
        # keys.verify-equivalent: strict canonical prechecks, then OpenSSL
        if not keys._canonical(pub, sig):
            return False
        try:
            Ed25519PublicKey.from_public_bytes(pub).verify(sig, msg)
            return True
        except (InvalidSignature, ValueError):
            return False

except ImportError:
    openssl_verdict = None

L = (1 << 252) + 27742317777372353535851937790883648493
P = (1 << 255) - 19

# RFC 8032-adjacent small-order point encodings (order divides 8):
# identity, the order-2 point, and the canonical order-4/8 encodings.
LOW_ORDER_ENCODINGS = [
    (1).to_bytes(32, "little"),                      # identity (y=1)
    (P - 1).to_bytes(32, "little"),                  # order 2 (y=-1)
    (0).to_bytes(32, "little"),                      # order 4 (y=0, x even)
    bytes.fromhex(                                   # order 8
        "c7176a703d4dd84fba3c0b760d10670f2a2053fa2c39ccc64ec7fd7792ac03fa"
    ),
    bytes.fromhex(                                   # order 8 (conjugate)
        "26e8958fc2b227b045c3f489f2ef98f0d5dfac05d3c63339b13802886d53fc05"
    ),
]


def h_scalar(pub: bytes, sig: bytes, msg: bytes) -> bytes:
    return hb.reduce512(hashlib.sha512(sig[:32] + pub + msg).digest())


def native_verdict(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Engine-level native verdict (no prechecks, no cache)."""
    return hb.verify_batch(pub, sig, h_scalar(pub, sig, msg)) == b"\x01"


def python_verdict(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Engine-level pure-Python verdict (no prechecks), bypassing the
    native routing in hostfallback.verify."""
    h_digest = hashlib.sha512(sig[:32] + pub + msg).digest()
    return hf._verify_cached(bytes(pub), bytes(sig), h_digest)


def assert_engines_agree(pub: bytes, msg: bytes, sig: bytes, why: str):
    n = native_verdict(pub, msg, sig)
    p = python_verdict(pub, msg, sig)
    assert n == p, f"{why}: native={n} python={p}"
    if openssl_verdict is not None and keys._canonical(pub, sig):
        # OpenSSL compared only inside the canonical domain keys.verify
        # admits — outside it the strict prechecks answer for every engine.
        assert openssl_verdict(pub, msg, sig) == n, why
    return n


def test_valid_and_mutated_signatures_agree():
    rng = random.Random(1234)
    seed = bytes(rng.randrange(256) for _ in range(32))
    pub = hf.public_from_seed(seed)
    accepted = rejected = 0
    for i in range(120):
        msg = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 300)))
        sig = bytearray(hf.sign(seed, msg))
        mode = i % 4
        if mode == 1:
            sig[rng.randrange(64)] ^= 1 << rng.randrange(8)  # bit-flip forgery
        elif mode == 2:
            sig[32:] = os.urandom(32)  # random scalar
        elif mode == 3:
            sig[:32] = os.urandom(32)  # random R (often not a point)
        verdict = assert_engines_agree(pub, msg, bytes(sig), f"case {i} mode {mode}")
        accepted += verdict
        rejected += not verdict
    assert accepted and rejected  # the sweep exercised both verdicts


def test_wrong_key_and_cross_signature_forgeries_rejected():
    a, b = keys.generate_keypair(), keys.generate_keypair()
    msg = b"forgery-target"
    sig = a.sign(msg)
    assert keys.verify(a.public_key, msg, sig)
    assert not keys.verify(b.public_key, msg, sig)  # wrong key
    assert not keys.verify(a.public_key, b"other", sig)  # wrong message
    assert not keys.verify(a.public_key, msg, b.sign(msg))  # wrong signer
    for case in [
        (b.public_key, msg, sig),
        (a.public_key, b"other", sig),
        (a.public_key, msg, b.sign(msg)),
    ]:
        assert assert_engines_agree(*case, why="forgery") is False


def test_non_canonical_s_engine_parity_and_keys_rejection():
    """s' = s + L names the same group element ([s']B == [s]B), so BOTH
    raw engines accept it — and keys.verify's strict canonical precheck
    rejects it for every engine identically (the malleability gate lives
    at ONE layer, not per engine)."""
    kp = keys.generate_keypair()
    msg = b"malleability"
    sig = kp.sign(msg)
    s = int.from_bytes(sig[32:], "little")
    assert s < L
    s_mall = s + L
    assert s_mall < 1 << 256  # representable: the engines must agree on it
    mall = sig[:32] + s_mall.to_bytes(32, "little")
    assert keys.verify(kp.public_key, msg, sig)
    assert not keys.verify(kp.public_key, msg, mall)  # strict precheck
    # engine level: both accept the alias, i.e. they AGREE
    assert native_verdict(kp.public_key, msg, mall) is True
    assert python_verdict(kp.public_key, msg, mall) is True


def test_non_canonical_y_rejected_by_both_engines():
    kp = keys.generate_keypair()
    msg = b"bad-point"
    sig = kp.sign(msg)
    for y in (P, P + 1, (1 << 255) - 20):
        bad = y.to_bytes(32, "little")
        assert assert_engines_agree(bad, msg, sig, f"pub y={y}") is False
        bad_sig = bad + sig[32:]
        assert (
            assert_engines_agree(kp.public_key, msg, bad_sig, f"R y={y}") is False
        )


def test_low_order_points_agree():
    """Cofactorless verification has exact, engine-independent semantics
    for small-order keys: with A = identity, [S]B == R + [h]A reduces to
    [S]B == R, so (R=[r]B, s=r) "verifies" for ANY message under either
    engine.  The differential contract is agreement, and the constructed
    cases prove the low-order branch is actually exercised."""
    rng = random.Random(7)
    identity = LOW_ORDER_ENCODINGS[0]
    r = rng.randrange(L)
    r_enc = hf._compress(hf._mul_base(r))
    sig = r_enc + r.to_bytes(32, "little")
    for msg in (b"", b"any message at all"):
        assert native_verdict(identity, msg, sig) is True
        assert python_verdict(identity, msg, sig) is True
    # every low-order encoding decompresses (or fails) identically
    kp = keys.generate_keypair()
    honest = kp.sign(b"m")
    for enc in LOW_ORDER_ENCODINGS:
        assert_engines_agree(enc, b"m", honest, f"low-order pub {enc.hex()[:16]}")
        assert_engines_agree(kp.public_key, b"m", enc + honest[32:],
                             f"low-order R {enc.hex()[:16]}")


def test_sign_native_matches_pure_python_reference():
    """Native sign must be BIT-identical to the pure-Python reference
    (RFC 8032 deterministic; the replica's own-grant re-sign-and-compare
    depends on equality across engines and restarts)."""
    rng = random.Random(99)
    for i in range(40):
        seed = bytes(rng.randrange(256) for _ in range(32))
        msg = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 200)))
        native_sig = hf.sign(seed, msg)  # routed through sign_prepared
        a, prefix, pub = hf._expand_seed(seed)
        r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
        r_bytes = hf._compress(hf._mul_base(r))
        k = int.from_bytes(
            hashlib.sha512(r_bytes + pub + msg).digest(), "little"
        ) % L
        expect = r_bytes + ((r + k * a) % L).to_bytes(32, "little")
        assert native_sig == expect, i
        assert keys.verify(pub, msg, native_sig)


def test_engine_identity_and_routing():
    if keys._HAVE_HOST_CRYPTO:
        assert keys.host_crypto_engine() == "openssl"
    else:
        assert hf.has_native()
        assert keys.host_crypto_engine() == "native-c"
        # native engines keep no per-signer state: registration reports
        # unrouted so callers don't credit a warmup that doesn't exist
        assert keys.register_known_signers([keys.generate_keypair().public_key]) is False


def test_verify_batch_rejects_inconsistent_buffers():
    with pytest.raises(ValueError):
        hb.verify_batch(b"\x00" * 32, b"\x00" * 64, b"\x00" * 31)
    with pytest.raises(ValueError):
        hb.verify_batch(b"\x00" * 31, b"\x00" * 64, b"\x00" * 32)
    with pytest.raises(ValueError):
        hb.verify_batch(b"\x00" * 32, b"\x00" * 63, b"\x00" * 32)
    assert hb.verify_batch(b"", b"", b"") == b""


def test_verify_batch_isolates_items():
    """One forged item in a batch fails alone (bitmap semantics match the
    SPI contract the replica's pooled round trip relies on)."""
    kp = keys.generate_keypair()
    msgs = [b"item-%d" % i for i in range(8)]
    sigs = [bytearray(kp.sign(m)) for m in msgs]
    sigs[3][7] ^= 1
    sigs[6][40] ^= 1
    pubs = b"".join([kp.public_key] * 8)
    hs = b"".join(
        h_scalar(kp.public_key, bytes(s), m) for s, m in zip(sigs, msgs)
    )
    bitmap = hb.verify_batch(pubs, b"".join(bytes(s) for s in sigs), hs)
    assert bitmap == bytes(
        1 if i not in (3, 6) else 0 for i in range(8)
    )

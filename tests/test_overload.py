"""Bounded-state + backpressure + wakeup-coalescing mechanics (config-9
tentpole, docs/OPERATIONS.md §4g).

What must hold at front-end scale (thousands of concurrent client
sessions): the replica's session table is LRU+TTL bounded and NEVER evicts
a session whose request is mid-batch; the client's per-connection msg-id
correlation map is bounded by refusing NEW work (typed), never by evicting
an in-flight entry; a slow reader trips the transport's send-queue
watermarks into pausing that connection's reads; request timeouts and
backoff sleeps coalesce onto one coarse timer wheel.
"""

from __future__ import annotations

import asyncio

import pytest

from mochi_tpu.net import transport as tp
from mochi_tpu.net.transport import PendingLimitExceeded, _Connection
from mochi_tpu.cluster.config import ServerInfo
from mochi_tpu.server.admission import AdmissionController, SessionTable, TokenBucket
from mochi_tpu.utils.wakeup import TimerWheel


# ------------------------------------------------------------ session table


def test_session_table_lru_eviction_and_bounds():
    t = SessionTable(max_entries=3, ttl_s=0)
    t["a"] = b"ka"
    t["b"] = b"kb"
    t["c"] = b"kc"
    assert t.get("a") == b"ka"  # refreshes recency: b is now LRU-oldest
    t["d"] = b"kd"
    assert len(t) == 3 and t.evictions == 1
    assert "b" not in t and "a" in t and "d" in t


def test_session_table_never_evicts_pinned_entry():
    """The regression the batch pipeline depends on: a sender pinned for an
    in-flight batch survives capacity eviction; the unpinned LRU entry goes
    instead — and a fully-pinned table admits over cap rather than corrupt
    a batch."""
    t = SessionTable(max_entries=2, ttl_s=0)
    t["inflight"] = b"k1"
    t["idle"] = b"k2"
    t.pin("inflight")
    t["new"] = b"k3"  # capacity eviction must skip the pinned entry
    assert "inflight" in t and "new" in t and "idle" not in t
    t.pin("new")
    t["another"] = b"k4"  # everything pinned: admit over cap, evict nothing
    assert len(t) == 3 and "inflight" in t and "new" in t
    t.unpin("inflight")
    t.unpin("new")
    # TTL sweep honors pins the same way
    t2 = SessionTable(max_entries=8, ttl_s=1e-9)
    t2["busy"] = b"k"
    t2["stale"] = b"k"
    t2.pin("busy")
    import time

    time.sleep(0.002)
    t2.sweep()
    assert "busy" in t2 and "stale" not in t2


def test_session_table_policy_evict_defers_on_pinned():
    """The safe eviction hook (replica.evict_client seam): an unpinned
    sender drops immediately; a pinned (mid-batch) one is deferred to its
    final unpin so in-flight responses still seal; a fresh handshake
    supersedes a pending deferred drop (the ban book, not eviction timing,
    keeps an evicted client out)."""
    t = SessionTable(max_entries=4, ttl_s=0)
    t["idle"] = b"k1"
    assert t.evict("idle") == "evicted" and "idle" not in t
    assert t.evict("idle") == "absent"
    t["busy"] = b"k2"
    t.pin("busy")
    t.pin("busy")  # nested pin: two envelopes of one drain
    assert t.evict("busy") == "deferred"
    assert t.get("busy") == b"k2"  # still live mid-batch
    t.unpin("busy")
    assert "busy" in t  # first unpin: still one pin outstanding
    t.unpin("busy")
    assert "busy" not in t and t.evictions == 2  # dropped at FINAL unpin
    t["back"] = b"k3"
    t.pin("back")
    assert t.evict("back") == "deferred"
    t["back"] = b"k4"  # re-handshake while pinned clears the deferral
    t.unpin("back")
    assert t.get("back") == b"k4"


def test_replica_session_pinned_across_batch_await():
    """End-to-end pin: a MAC'd request mid-batch must keep its session
    alive even when a same-batch handshake lands in a full table — the
    response must seal under the surviving session, not bounce."""
    from mochi_tpu.cluster.config import ClusterConfig
    from mochi_tpu.crypto import session as session_crypto
    from mochi_tpu.crypto.keys import generate_keypair
    from mochi_tpu.protocol import (
        Envelope,
        NudgeSyncToServer,
        SessionInitToServer,
        SyncAckFromServer,
    )
    from mochi_tpu.net.transport import new_msg_id
    from mochi_tpu.server.replica import MochiReplica

    async def main():
        kps = {f"server-{i}": generate_keypair() for i in range(4)}
        kp = kps["server-0"]
        config = ClusterConfig.build(
            {sid: f"127.0.0.1:{i + 1}" for i, sid in enumerate(kps)},
            rf=4,
            public_keys={sid: k.public_key for sid, k in kps.items()},
        )
        replica = MochiReplica("server-0", config, kp, admission=False)
        replica._sessions = SessionTable(max_entries=1, ttl_s=0)
        session_key = b"\x07" * 32
        replica._sessions["client-A"] = session_key

        macd = session_crypto.seal(
            Envelope(
                payload=NudgeSyncToServer(("k",)),
                msg_id=new_msg_id(),
                sender_id="client-A",
                timestamp_ms=0,
            ),
            session_key,
        )
        hs = session_crypto.new_handshake()
        init_kp = generate_keypair()
        init_env = Envelope(
            payload=SessionInitToServer(hs.public_bytes, hs.nonce),
            msg_id=new_msg_id(),
            sender_id="client-B",
            timestamp_ms=0,
        )
        init_env = init_env.with_signature(init_kp.sign(init_env.signing_bytes()))

        # one batch: the MAC'd request pins client-A; client-B's handshake
        # insert hits a FULL table and must not evict the pinned session
        responses = await replica.handle_batch([macd, init_env])
        assert isinstance(responses[0].payload, SyncAckFromServer)
        assert responses[0].mac is not None  # sealed under the LIVE session
        assert replica._sessions.get("client-A") == session_key
        await replica.close()

    asyncio.run(main())


# --------------------------------------------------------- pending-map bound


def test_pending_map_full_refuses_new_never_evicts_inflight():
    """msg-id correlation map at the cap: the NEW request fails typed;
    every in-flight future survives untouched (evicting one would orphan
    its response into a spurious timeout)."""

    async def main():
        conn = _Connection(
            ServerInfo("s0", "127.0.0.1", 1), pending_max=4
        )
        loop = asyncio.get_running_loop()
        futs = {f"m{i}": loop.create_future() for i in range(4)}
        for mid, fut in futs.items():
            conn.register_pending(mid, fut)
        with pytest.raises(PendingLimitExceeded):
            conn.register_pending("m-overflow", loop.create_future())
        assert set(conn.pending) == set(futs)  # nothing in-flight evicted
        # resolved leftovers ARE swept to make room
        futs["m0"].set_result(None)
        conn.register_pending("m-next", loop.create_future())
        assert "m0" not in conn.pending and "m-next" in conn.pending
        assert all(not f.done() or mid == "m0" for mid, f in futs.items())

    asyncio.run(main())


# ------------------------------------------------------ send-queue watermarks


def test_sendq_accounting_and_flow_pause_bookkeeping():
    """Transport-side bookkeeping behind the admission signal: buffered
    response bytes are counted in and out, pause_writing marks the
    connection (and the server tally), and a connection lost while paused
    does not leak the count."""

    class _FakeTransport:
        def __init__(self):
            self.paused = False
            self.written = b""

        def is_closing(self):
            return False

        def pause_reading(self):
            self.paused = True

        def resume_reading(self):
            self.paused = False

        def write(self, data):
            self.written += data

        def get_write_buffer_size(self):
            return 0

        def set_write_buffer_limits(self, high=None, low=None):
            self.limits = (high, low)

    async def main():
        server = tp.RpcServer("127.0.0.1", 0, handler=None)
        proto = tp._RpcServerProtocol(server)
        t = _FakeTransport()
        proto.connection_made(t)
        assert t.limits == (server.sendq_high, server.sendq_low)

        touched = []
        proto.queue_frame(b"x" * 100, touched)
        assert server._sendq_out_bytes == 104  # payload + length prefix
        proto.flush_now()
        assert server._sendq_out_bytes == 0 and len(t.written) == 104

        proto.pause_writing()
        assert t.paused and server._paused_conns == 1
        assert server.load_stats()["paused_conns"] == 1
        proto.resume_writing()
        assert not t.paused and server._paused_conns == 0

        # lost-while-paused: the tally and byte count must not leak
        proto.queue_frame(b"y" * 10, touched)
        proto.pause_writing()
        proto.connection_lost(None)
        assert server._paused_conns == 0 and server._sendq_out_bytes == 0

    asyncio.run(main())


def test_admission_controller_excess_demand_curve():
    """shed_p tracks the excess-demand fraction 1 - 1/L of the WORST load
    component, smoothed per update; below every high-water mark it decays
    to exactly 0."""

    class _FakeRpc:
        def __init__(self):
            self.stats = {
                "batch_ewma": 0.0, "inflight_envs": 0,
                "sendq_out_bytes": 0, "paused_conns": 0,
                "ingress_depth": 0, "connections": 0,
            }

        def load_stats(self):
            return self.stats

    rpc = _FakeRpc()
    ac = AdmissionController(rpc, enabled=True, inflight_hw=100)
    ac.update()
    assert ac.shed_p == 0.0 and not ac.overloaded and ac.retry_after_ms == 0
    rpc.stats["inflight_envs"] = 200  # L = 2: steady-state target 0.5
    for _ in range(12):
        ac.update()
    assert ac.overloaded and abs(ac.shed_p - 0.5) < 0.01
    assert ac.retry_after_ms == 50  # 25 ms per unit load
    rpc.stats["inflight_envs"] = 0
    for _ in range(20):
        ac.update()
    assert ac.shed_p == 0.0 and not ac.overloaded
    # pin wins over the signal (test seam)
    ac.pin(1.0)
    rpc.stats["inflight_envs"] = 0
    ac.update()
    assert ac.shed_p == 1.0


# ------------------------------------------------------- handshake rate limit


def test_handshake_rate_limit_client_falls_back_to_signatures():
    """A replica out of handshake tokens refuses typed OVERLOADED with a
    retry-after; the client caches the refusal (no re-knock per request)
    and the write still commits on signed envelopes — the valve costs the
    MAC discount, never liveness."""
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            for r in vc.replicas:
                r._handshakes = TokenBucket(rate_per_s=0.001, burst=0)
            client = vc.client(timeout_s=5.0)
            await client.execute_write_transaction(
                TransactionBuilder().write("k", b"v").build()
            )
            assert not client._sessions  # every handshake was refused
            assert client._session_refused  # ...and cached, not re-knocked
            limited = sum(
                n
                for name, n in client.metrics.counters.items()
                if name.startswith("client.handshake-limited.")
            )
            assert limited >= 1
            refused = sum(r._handshakes.refused for r in vc.replicas)
            assert refused >= 1
            res = await client.execute_read_transaction(
                TransactionBuilder().read("k").build()
            )
            assert res.operations[0].value == b"v"

    asyncio.run(main())


# ------------------------------------------------------------ wakeup wheel


def test_timer_wheel_coalesces_and_never_fires_early():
    async def main():
        wheel = TimerWheel(quantum_s=0.02)
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        # many sleeps landing in the same quantum share buckets
        await asyncio.gather(*(wheel.sleep(0.03) for _ in range(50)))
        elapsed = loop.time() - t0
        assert elapsed >= 0.03, f"wheel fired early ({elapsed:.4f}s)"
        assert elapsed < 0.5
        st = wheel.stats()
        assert st["scheduled"] == 50 and st["fired"] == 50
        # cancellation is lazy and cheap: a cancelled entry never fires
        fired = []
        entry = wheel.call_later(0.03, lambda: fired.append(1))
        entry.cancel()
        await asyncio.sleep(0.08)
        assert not fired and wheel.stats()["lapsed"] >= 1
        wheel.close()

    asyncio.run(main())


def test_send_and_receive_timeout_rides_the_wheel():
    """A server that never answers: the wheel-based timeout raises
    asyncio.TimeoutError within timeout + one quantum, and the pending
    map entry is reclaimed."""
    from mochi_tpu.protocol import Envelope, HelloToServer
    from mochi_tpu.net.transport import RpcServer, RpcClientPool, new_msg_id

    async def main():
        async def blackhole(env):
            await asyncio.sleep(30)

        server = RpcServer("127.0.0.1", 0, blackhole)
        await server.start()
        pool = RpcClientPool(default_timeout_s=0.2)
        info = ServerInfo("s0", "127.0.0.1", server.bound_port)
        env = Envelope(
            payload=HelloToServer("hi"), msg_id=new_msg_id(),
            sender_id="c", timestamp_ms=0,
        )
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        with pytest.raises(asyncio.TimeoutError):
            await pool.send_and_receive(info, env, timeout_s=0.2)
        elapsed = loop.time() - t0
        assert 0.2 <= elapsed < 0.5
        conn = pool._conn(info)
        assert not conn.pending  # reclaimed on timeout
        await pool.close()
        await server.close()

    asyncio.run(main())


# ------------------------------------------- suspicion-steered trim_write1


def test_trim_write1_first_attempt_avoids_suspect_peer():
    """ISSUE 8 satellite: the per-peer suspicion scores (PR 7) steer the
    quorum-trimmed FIRST Write1 attempt exactly as they steer trimmed
    reads — both ride ``_quorum_targets``.  With one in-set peer past the
    suspicion threshold, a trim_write1 client's first attempt must not
    send it a Write1 at all (rf=4, quorum=3: coverage without the suspect
    is always possible)."""
    import time as _time

    from mochi_tpu.client.client import SUSPICION_THRESHOLD
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client(timeout_s=5.0, trim_write1=True)
            # warm sessions so per-replica counters start clean-ish
            await client.execute_write_transaction(
                TransactionBuilder().write("warm", b"v").build()
            )
            key = "trimtest"
            in_set = client.config.replica_set_for_key(key)
            suspect = in_set[0]
            events = client._suspicion_events.setdefault(
                suspect, __import__("collections").deque(maxlen=4096)
            )
            now = _time.monotonic()
            events.extend([now] * (SUSPICION_THRESHOLD + 3))

            before = {
                sid: vc.replica(sid).metrics.timers["replica.write1"].count
                for sid in in_set
            }
            await client.execute_write_transaction(
                TransactionBuilder().write(key, b"x").build()
            )
            after = {
                sid: vc.replica(sid).metrics.timers["replica.write1"].count
                for sid in in_set
            }
            served = {sid for sid in in_set if after[sid] > before[sid]}
            assert suspect not in served, (
                f"suspect {suspect} still got the trimmed first Write1"
            )
            # the quorum still covered: at least quorum peers served it
            assert len(served) >= client.config.quorum

    asyncio.run(main())


# -------------------------------------------- invariant in-doubt semantics


def test_invariant_checker_in_doubt_write_is_not_loss_but_real_loss_is():
    """Round-12 checker semantics: a write that FAILED at the client after
    dispatch may still have committed (frame loss ate the answers) — if
    the re-read returns such an in-doubt value, durability held and the
    checker must not cry loss.  A value the cluster never saw acked OR
    attempted remains a hard violation (the check stays non-vacuous)."""
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.invariants import InvariantChecker
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client(timeout_s=5.0)
            checker = InvariantChecker(vc.replicas)
            await client.execute_write_transaction(
                TransactionBuilder().write("k", b"v1").build()
            )
            checker.record_ack("k", b"v1")
            # the "failed at client, committed at cluster" shape: the write
            # really lands, but the workload only records an attempt
            await client.execute_write_transaction(
                TransactionBuilder().write("k", b"v2").build()
            )
            checker.record_attempt("k", b"v2")
            await checker.final_check(client)
            assert checker.ok, checker.violations
            assert checker.in_doubt_accepted == 1
            # real loss still fires: claim an ack the cluster never served
            checker2 = InvariantChecker(vc.replicas)
            checker2.record_ack("k", b"v3-never-written")
            await checker2.final_check(client)
            assert not checker2.ok
            assert "lost" in checker2.violations[0]
            # and a LATER ack clears older in-doubt values: a stale
            # in-doubt value re-surfacing after a newer ack is loss
            checker3 = InvariantChecker(vc.replicas)
            checker3.record_attempt("q", b"old")
            checker3.record_ack("q", b"new")
            assert checker3._in_doubt.get("q") is None

    asyncio.run(main())


def test_batch_ewma_resets_after_idle_gap():
    """The congestion EWMA is only folded when frames arrive — without the
    idle-gap reset, a storm's EWMA would freeze across hours of silence
    and shed the first writes of the next burst from an IDLE replica."""
    from mochi_tpu.protocol import Envelope, HelloToServer
    from mochi_tpu.net.transport import new_msg_id

    async def main():
        async def handler(env):
            return None

        server = tp.RpcServer("127.0.0.1", 0, handler)
        proto = tp._RpcServerProtocol(server)
        env = Envelope(
            payload=HelloToServer("hi"), msg_id=new_msg_id(),
            sender_id="c", timestamp_ms=0,
        )
        import time as _time

        # a storm parked the EWMA high, then the replica went idle
        server._batch_ewma = 640.0
        server._last_drain_t = _time.perf_counter() - 5.0
        server._ingress.append((proto, env))
        server._drain()
        await asyncio.sleep(0)  # let the spawned handler task run
        assert server._batch_ewma < 1.0, server._batch_ewma
        # back-to-back drains (no idle gap) keep folding normally
        server._ingress.append((proto, env))
        server._drain()
        await asyncio.sleep(0)
        assert 0 < server._batch_ewma < 2.0

    asyncio.run(main())

"""Tier-1 gate: the tree is lint-clean under mochi_tpu.analysis.

Two guarantees, both via the same CLI every future PR runs
(``scripts/lint.sh``):

1. ``python -m mochi_tpu.analysis mochi_tpu/ scripts/`` exits 0 on the
   current tree — a new finding anywhere fails this test, so the checkers
   gate every PR through the existing pytest tier-1 hook;
2. each of the five seeded regression fixtures (one per checker), dropped
   into a scanned tree, flips the exit code to non-zero — the checkers
   can't silently rot into no-ops.
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "analysis_fixtures")
BASELINE = os.path.join(REPO, "config", "analysis_baseline.json")


def run_cli(*args: str, cwd: str = REPO) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    # the repo may be run from a checkout without `pip install -e .`
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "mochi_tpu.analysis", *args],
        cwd=cwd,
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_tree_is_lint_clean():
    proc = run_cli("mochi_tpu/", "scripts/")
    assert proc.returncode == 0, f"new findings:\n{proc.stdout}{proc.stderr}"


def test_baseline_file_is_empty():
    # The shipped baseline grandfathers nothing: every finding on the tree
    # is fixed or carries an explicit justified suppression.  A PR that
    # re-baselines instead of fixing turns this red.
    import json

    with open(BASELINE) as fh:
        doc = json.load(fh)
    assert doc["fingerprints"] == []


SEEDED = [
    "async_blocking_bad.py",
    "cancellation_bad.py",
    "trace_safety_bad.py",
    "const_time_bad.py",
    "invariants_bad.py",
    "await_races_bad.py",
    "native_ct_bad.c",
    "span_lazy_bad.py",
    "wire_taint_bad.py",
    "unbounded_growth_bad.py",
]


@pytest.mark.parametrize("bad_fixture", SEEDED)
def test_seeded_regression_flips_exit_code(bad_fixture, tmp_path):
    # Simulate the regression landing in a scanned package: the fixture is
    # copied into a fresh tree and the CLI must go non-zero on it.
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    shutil.copy(os.path.join(FIXTURES, bad_fixture), pkg / bad_fixture)
    proc = run_cli(str(pkg), "--no-path-filter", cwd=str(tmp_path))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "[" in proc.stdout  # at least one rendered finding


def test_clean_file_exits_zero(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "ok.py").write_text("import asyncio\n\nasync def f():\n    await asyncio.sleep(1)\n")
    proc = run_cli(str(pkg), "--no-path-filter", cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ------------------------------------------------------- diff-aware strict


BAD_SRC = "import time\nasync def f():\n    time.sleep(1)\n"
OK_SRC = "import asyncio\nasync def f():\n    await asyncio.sleep(1)\n"


def _git(repo: str, *args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=repo, capture_output=True, text=True, timeout=30,
    )


@pytest.fixture
def diff_repo(tmp_path):
    """A throwaway git repo: pkg/old.py (committed, has a finding) and
    pkg/new.py (untracked, has a finding)."""
    repo = tmp_path / "repo"
    (repo / "pkg").mkdir(parents=True)
    assert _git(str(tmp_path), "init", "-q", str(repo)).returncode == 0
    (repo / "pkg" / "old.py").write_text(BAD_SRC)
    _git(str(repo), "add", "-A")
    assert _git(str(repo), "commit", "-q", "-m", "seed").returncode == 0
    (repo / "pkg" / "new.py").write_text(BAD_SRC)
    return str(repo)


def test_changed_only_fails_on_changed_warns_on_rest(diff_repo):
    proc = run_cli("pkg", "--changed-only", "HEAD", "--no-path-filter", cwd=diff_repo)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    failing = [
        ln for ln in proc.stdout.splitlines()
        if ln.startswith("pkg/new.py") and "[async-blocking" in ln
    ]
    warned = [
        ln for ln in proc.stdout.splitlines()
        if ln.startswith("warning") and "pkg/old.py" in ln
    ]
    assert failing and warned, proc.stdout


def test_changed_only_exits_zero_when_only_unchanged_files_dirty(diff_repo):
    os.remove(os.path.join(diff_repo, "pkg", "new.py"))
    proc = run_cli("pkg", "--changed-only", "HEAD", "--no-path-filter", cwd=diff_repo)
    # old.py's finding is pre-existing debt, not this PR's — warn, exit 0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "pkg/old.py" in proc.stdout and "warning" in proc.stdout


def test_changed_only_catches_working_tree_edit(diff_repo):
    # an EDITED (not just untracked) file fails too: diff vs REF covers the
    # working tree, not only commits
    os.remove(os.path.join(diff_repo, "pkg", "new.py"))
    with open(os.path.join(diff_repo, "pkg", "old.py"), "a") as fh:
        fh.write("\nasync def g():\n    time.sleep(2)\n")
    proc = run_cli("pkg", "--changed-only", "HEAD", "--no-path-filter", cwd=diff_repo)
    assert proc.returncode == 1, proc.stdout + proc.stderr


def test_changed_only_unknown_ref_falls_back_to_full_strict(diff_repo):
    proc = run_cli(
        "pkg", "--changed-only", "no-such-ref", "--no-path-filter", cwd=diff_repo
    )
    # never silently passes: git can't answer -> every finding fails
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "falling back to full-strict" in proc.stderr


def test_changed_only_from_subdir_anchors_at_repo_root(diff_repo):
    # git reports repo-root-relative names; invoked from a SUBDIR with an
    # absolute path arg, the changed set must still match — an empty set
    # here would downgrade the new file's finding to a warning (silent pass)
    proc = run_cli(
        os.path.join(diff_repo, "pkg"), "--changed-only", "HEAD",
        "--no-path-filter", cwd=os.path.join(diff_repo, "pkg"),
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert any(
        ln.startswith("pkg/new.py") and not ln.startswith("warning")
        for ln in proc.stdout.splitlines()
    ), proc.stdout


def test_changed_only_diffs_the_scanned_repo_not_the_cwd(diff_repo, tmp_path):
    # The changed set must come from the SCANNED repo: gating repoB from a
    # cwd inside repoA used to diff repoA, see nothing changed, and
    # downgrade repoB's brand-new finding to a warning — a silent pass on
    # the gate's own fail-closed contract.
    other = tmp_path / "other"
    other.mkdir()
    assert _git(str(tmp_path), "init", "-q", str(other)).returncode == 0
    (other / "seed.py").write_text(OK_SRC)
    _git(str(other), "add", "-A")
    assert _git(str(other), "commit", "-q", "-m", "seed").returncode == 0
    proc = run_cli(
        os.path.join(diff_repo, "pkg"), "--changed-only", "HEAD",
        "--no-path-filter", cwd=str(other),
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert any(
        "new.py" in ln and not ln.startswith("warning")
        for ln in proc.stdout.splitlines()
        if "[async-blocking" in ln
    ), proc.stdout


def test_changed_display_paths_fails_closed_outside_repo(tmp_path, monkeypatch):
    # no repo -> None (full-strict fallback), never an empty changed set
    from mochi_tpu.analysis.__main__ import changed_display_paths

    monkeypatch.chdir(tmp_path)
    assert changed_display_paths("HEAD") is None


def test_changed_only_matches_nested_non_package_dirs(diff_repo):
    # Finding display paths anchor at the scan root; the changed set is
    # absolute and membership is suffix-matched — a nested dir WITHOUT
    # __init__.py (where the two anchorings diverge) must still FAIL on
    # its changed file, not downgrade it to a warning.
    sub = os.path.join(diff_repo, "pkg", "sub")
    os.makedirs(sub)
    with open(os.path.join(sub, "nested_new.py"), "w") as fh:
        fh.write(BAD_SRC)
    proc = run_cli("pkg", "--changed-only", "HEAD", "--no-path-filter", cwd=diff_repo)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert any(
        "nested_new.py" in ln and not ln.startswith("warning")
        for ln in proc.stdout.splitlines()
        if "[async-blocking" in ln
    ), proc.stdout

"""Tier-1 gate: the tree is lint-clean under mochi_tpu.analysis.

Two guarantees, both via the same CLI every future PR runs
(``scripts/lint.sh``):

1. ``python -m mochi_tpu.analysis mochi_tpu/ scripts/`` exits 0 on the
   current tree — a new finding anywhere fails this test, so the checkers
   gate every PR through the existing pytest tier-1 hook;
2. each of the five seeded regression fixtures (one per checker), dropped
   into a scanned tree, flips the exit code to non-zero — the checkers
   can't silently rot into no-ops.
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "analysis_fixtures")
BASELINE = os.path.join(REPO, "config", "analysis_baseline.json")


def run_cli(*args: str, cwd: str = REPO) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    # the repo may be run from a checkout without `pip install -e .`
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "mochi_tpu.analysis", *args],
        cwd=cwd,
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_tree_is_lint_clean():
    proc = run_cli("mochi_tpu/", "scripts/")
    assert proc.returncode == 0, f"new findings:\n{proc.stdout}{proc.stderr}"


def test_baseline_file_is_empty():
    # The shipped baseline grandfathers nothing: every finding on the tree
    # is fixed or carries an explicit justified suppression.  A PR that
    # re-baselines instead of fixing turns this red.
    import json

    with open(BASELINE) as fh:
        doc = json.load(fh)
    assert doc["fingerprints"] == []


SEEDED = [
    "async_blocking_bad.py",
    "cancellation_bad.py",
    "trace_safety_bad.py",
    "const_time_bad.py",
    "invariants_bad.py",
]


@pytest.mark.parametrize("bad_fixture", SEEDED)
def test_seeded_regression_flips_exit_code(bad_fixture, tmp_path):
    # Simulate the regression landing in a scanned package: the fixture is
    # copied into a fresh tree and the CLI must go non-zero on it.
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    shutil.copy(os.path.join(FIXTURES, bad_fixture), pkg / bad_fixture)
    proc = run_cli(str(pkg), "--no-path-filter", cwd=str(tmp_path))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "[" in proc.stdout  # at least one rendered finding


def test_clean_file_exits_zero(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "ok.py").write_text("import asyncio\n\nasync def f():\n    await asyncio.sleep(1)\n")
    proc = run_cli(str(pkg), "--no-path-filter", cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr

"""Large-cluster protocol correctness: the BASELINE north-star shapes.

Through round 4 no cluster larger than 6 replicas had ever booted (VERDICT
r4 missing #1) while BASELINE.json's headline metric is defined at n=64,
f=21.  These tests run the REAL protocol — full Write1 fan-out, quorum
certificate assembly + quorum-cover trimming, Write2 cert verification on
every replica — at the CI-sized n=16 f=5 shape (grounding config 3's
cluster scale) and an n=64 f=21 smoke, plus the comb registry at its
design size of 64 identities (crypto/comb.py:34 "n=64 clusters stay
~7.5 MB").

The reference supports RF up to n (``ClusterConfiguration.java:167-186``)
but its tests stop at rf=4; the quorum arithmetic exercised here
(f=(rf-1)//3, quorum=2f+1) only shows its corner cases at larger f — e.g.
losing exactly f replicas leaves exactly quorum members, so liveness holds
with zero slack.
"""

from __future__ import annotations

import asyncio

import pytest

from mochi_tpu.client.errors import MochiClientError
from mochi_tpu.client.txn import TransactionBuilder
from mochi_tpu.testing.virtual_cluster import VirtualCluster


def test_n16_f5_full_protocol():
    """n=16, rf=16 -> f=5, quorum=11: writes commit with 11-grant certs;
    killing f replicas keeps liveness with ZERO quorum slack; killing one
    more loses it (correct BFT refusal, not a bug)."""

    async def drive():
        async with VirtualCluster(16, rf=16) as vc:
            cfg = vc.config
            assert cfg.f == 5 and cfg.quorum == 11
            client = vc.client(timeout_s=30.0)

            await client.execute_write_transaction(
                TransactionBuilder().write("big16", b"v1").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("big16").build()
            )
            assert res.operations[0].value == b"v1"
            cert = res.operations[0].current_certificate
            # quorum-cover trimming must shave the rf-quorum surplus down
            # to exactly 2f+1 grants (client._trim_to_quorum_cover)
            assert cert is not None and len(cert.grants) == cfg.quorum

            # overwrite + multi-key through the same quorum machinery
            await client.execute_write_transaction(
                TransactionBuilder().write("big16", b"v2").write("big16b", b"w").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("big16").build()
            )
            assert res.operations[0].value == b"v2"

            # Lose exactly f replicas: quorum survives with zero slack.
            victims = [r for r in vc.replicas[: cfg.f]]
            for r in victims:
                await r.close()
            await client.execute_write_transaction(
                TransactionBuilder().write("big16", b"v3").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("big16").build()
            )
            assert res.operations[0].value == b"v3"

            # Lose one more (f+1 down): writes must fail — fewer than 2f+1
            # healthy members remain, so no certificate can form.
            await vc.replicas[cfg.f].close()
            fast = vc.client(timeout_s=2.0, write_attempts=1)
            with pytest.raises(MochiClientError):
                await fast.execute_write_transaction(
                    TransactionBuilder().write("big16", b"v4").build()
                )

    asyncio.run(drive())


def test_n64_f21_smoke():
    """The north-star shape itself: 64 replicas, f=21, one signed PUT
    committing a 43-grant certificate through the full 2-phase protocol."""

    async def drive():
        async with VirtualCluster(64, rf=64) as vc:
            cfg = vc.config
            assert cfg.f == 21 and cfg.quorum == 43
            client = vc.client(timeout_s=60.0)
            await client.execute_write_transaction(
                TransactionBuilder().write("north-star", b"n64").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("north-star").build()
            )
            assert res.operations[0].value == b"n64"
            cert = res.operations[0].current_certificate
            assert cert is not None and len(cert.grants) == 43

    asyncio.run(drive())


def test_comb_registry_at_design_size():
    """64 registered identities — the comb registry's design point: table
    device footprint ~7.5 MB, gathers spanning the full (64*576, 51) flat
    table.  Verdicts must stay differentially exact vs OpenSSL across all
    64 signers, including a forged item mid-batch."""
    import numpy as np

    from mochi_tpu.crypto import comb as comb_mod
    from mochi_tpu.crypto import keys
    from mochi_tpu.crypto.batch_verify import prepare_packed
    from mochi_tpu.verifier.spi import VerifyItem

    reg = comb_mod.SignerRegistry()
    kps = [keys.keypair_from_seed(bytes([i + 1] * 32)) for i in range(64)]
    for kp in kps:
        assert reg.register(kp.public_key) is not None
    assert len(reg) == 64

    items = []
    for i, kp in enumerate(kps):
        msg = b"design-size %d" % i
        items.append(VerifyItem(kp.public_key, msg, kp.sign(msg)))
    # one forgery mid-batch: signer 31's signature over a different message
    bad = 31
    items[bad] = VerifyItem(
        kps[bad].public_key, b"not what was signed", items[bad].signature
    )

    _, _, y_r, sign_r, s_sc, h_sc, pre_ok = prepare_packed(items)
    assert pre_ok.all()
    key_idx = np.asarray(
        [reg.index_of(it.public_key) for it in items], dtype=np.int32
    )
    table = reg.device_table()
    assert table.shape == (64 * comb_mod.N_WINDOWS * comb_mod.N_ENTRIES, comb_mod.ROW_WIDTH)
    out = np.asarray(
        comb_mod._verify_comb_jit(table, key_idx, y_r, sign_r, s_sc, h_sc)
    )
    expect = np.ones(64, bool)
    expect[bad] = False
    assert (out == expect).all(), np.nonzero(out != expect)


@pytest.mark.slow
def test_config6_shape_order_independence():
    """Run-order-independence regression for the config-6 GC-debt artifact
    (VERDICT r5 weak #4): an n=16 record taken AFTER an n=64 run must land
    within 10% of an n16-first record.

    Root cause (BASELINE.md "GC debt, root-caused"): the torn-down
    64-replica object graph is cyclic, so under the relaxed server GC
    thresholds it lingers uncollected while the next shape's allocations
    repeatedly trigger collections that trace the dead giant graph.
    ``reset_gc_debt()`` (collect + refreeze between shapes — what
    benchmarks/config6_bigcluster.py now does) is the fix under test.
    Marked slow: it is a timing comparison and runs real cluster
    workloads; the tier-1 gate stays fast without it.
    """
    from benchmarks.config6_bigcluster import _run_shape
    from mochi_tpu.utils.runtime import reset_gc_debt, tune_gc_for_server

    tune_gc_for_server()

    def n16_rate(reps: int = 3) -> float:
        # best-of-N, one-sided: tenancy noise only ever SLOWS a run, so
        # the max approaches the true rate (the repo's measurement rule —
        # never single runs on this ±30% host)
        rates = []
        for _ in range(reps):
            rec = asyncio.run(_run_shape(16, 4, 3, "cpu"))
            rates.append(rec["txn_per_s"])
            reset_gc_debt()
        return max(rates)

    # Up to two full attempts: a background-load window spanning one whole
    # best-of-3 leg (but not the other) is indistinguishable from a real
    # ordering effect within a single pair, so a failed comparison gets
    # one fresh pair before it is believed.
    last = None
    for _attempt in range(2):
        first = n16_rate()
        # generate the debt: a full n=64 boot + workload + teardown
        asyncio.run(_run_shape(64, 4, 2, "cpu"))
        reset_gc_debt()  # the config-6 fix under test
        after = n16_rate()
        if after >= 0.9 * first:
            return
        last = (after, first)
    after, first = last
    raise AssertionError(
        f"n16-after-n64 regressed past 10% in two independent pairs: "
        f"{after:.1f} vs {first:.1f} txn/s — GC debt is back "
        "(see BASELINE.md root cause)"
    )

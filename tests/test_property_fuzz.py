"""Property-based fuzzing (hypothesis): codec, envelope, signed-digit recode.

The mcode codec is the trust root of the whole signature scheme (wire bytes
== signing bytes), so its invariants get generative coverage beyond the
hand-picked cases in test_codec.py:

* round-trip identity for arbitrary nested values on the pure-Python
  reference implementation;
* canonicality: semantically equal inputs encode to identical bytes
  (dict insertion order must not matter — this is what makes signing
  bytes canonical);
* the C extension agrees byte-for-byte with the Python reference, on
  valid values AND on arbitrary garbage (accept/reject must match: a
  divergence would let an attacker craft frames that split replicas).
"""

import string

import pytest

# hypothesis is optional (absent on the bare CI image): generative tests
# skip individually, while the deterministic boundary sweeps below — which
# need no generator — keep running.  The shim keeps the @given-decorated
# definitions importable.
try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - exercised on bare environments
    HAVE_HYPOTHESIS = False

    def given(*_a, **_k):
        def deco(fn):
            return pytest.mark.skip(reason="hypothesis not installed")(fn)

        return deco

    def settings(*_a, **_k):
        def deco(fn):
            return fn

        return deco

    class _StrategyStub:
        def __getattr__(self, _name):
            return lambda *a, **k: None

    st = _StrategyStub()  # type: ignore[assignment]

from mochi_tpu.protocol import (  # noqa: E402
    Envelope,
    HelloToServer,
    decode_envelope,
    encode_envelope,
)
from mochi_tpu.protocol.codec import _decode_py, _encode_py  # noqa: E402

try:
    from mochi_tpu.native import get_mcode

    _native = get_mcode()
except Exception:  # pragma: no cover - cc unavailable
    _native = None

needs_native = pytest.mark.skipif(_native is None, reason="no C toolchain")


# mcode value domain: None/bool/int/bytes/str and lists/dicts thereof
_scalar = st.one_of(
    st.none(),
    st.booleans(),
    # full codec range incl. [2^63, 2^64) — where a signed-64-bit bug in
    # the C decoder would be most likely to diverge from Python bignum
    st.integers(min_value=-(2**64), max_value=2**64 - 1),
    st.binary(max_size=64),
    st.text(max_size=32),
)
_value = st.recursive(
    _scalar,
    lambda children: st.one_of(
        st.lists(children, max_size=6),
        st.dictionaries(st.text(string.printable, max_size=8), children, max_size=6),
    ),
    max_leaves=25,
)


@settings(max_examples=200, deadline=None)
@given(_value)
def test_python_codec_roundtrip(value):
    assert _decode_py(_encode_py(value)) == value


@settings(max_examples=100, deadline=None)
@given(st.dictionaries(st.text(max_size=8), _scalar, max_size=8))
def test_canonical_dict_order(d):
    """Insertion order must not leak into the canonical bytes."""
    reordered = dict(sorted(d.items(), reverse=True))
    assert _encode_py(reordered) == _encode_py(d)
    if _native is not None:
        assert _native.encode(reordered) == _native.encode(d)


@needs_native
@settings(max_examples=200, deadline=None)
@given(_value)
def test_native_matches_python(value):
    blob = _encode_py(value)
    assert _native.encode(value) == blob  # byte-identical canonical form
    assert _native.decode(blob) == value


@needs_native
@settings(max_examples=150, deadline=None)
@given(st.binary(max_size=96))
def test_decoders_never_crash_and_agree_on_garbage(blob):
    """Arbitrary bytes either decode identically on both paths or raise on
    both — a divergence would let an attacker craft frames that one replica
    accepts and another rejects."""
    try:
        py_val = _decode_py(blob)
        py_ok = True
    except Exception:
        py_ok = False
    try:
        c_val = _native.decode(blob)
        c_ok = True
    except Exception:
        c_ok = False
    assert py_ok == c_ok
    if py_ok:
        assert py_val == c_val


@settings(max_examples=50, deadline=None)
@given(
    msg=st.text(max_size=24),
    msg_id=st.text(string.hexdigits, min_size=1, max_size=32),
    sender=st.text(max_size=24),
    reply_to=st.one_of(st.none(), st.text(max_size=16)),
    ts=st.integers(min_value=0, max_value=2**53),
    sig=st.one_of(st.none(), st.binary(min_size=64, max_size=64)),
    mac=st.one_of(st.none(), st.binary(min_size=32, max_size=32)),
)
def test_envelope_roundtrip(msg, msg_id, sender, reply_to, ts, sig, mac):
    env = Envelope(HelloToServer(msg), msg_id, sender, reply_to, ts, sig, mac)
    back = decode_envelope(encode_envelope(env))
    assert back.payload == env.payload
    assert (back.msg_id, back.sender_id, back.reply_to) == (msg_id, sender, reply_to)
    assert (back.timestamp_ms, back.signature, back.mac) == (ts, sig, mac)
    # auth bytes never cover the auth fields
    assert back.signing_bytes() == env.signing_bytes()


def test_recode_signed4_exact_over_random_scalars():
    """Vectorized check: sum(mag * (-1)^neg * 16^k) reconstructs the scalar
    exactly for random scalars < 2^253 plus the edge cases."""
    import numpy as np

    import jax
    from mochi_tpu.crypto.curve import digits4_from_bits, recode_signed4

    rng = np.random.default_rng(7)
    scalars = [0, 1, (1 << 253) - 1, (1 << 252) + 27742317777372353535851937790883648492]
    scalars += [int.from_bytes(rng.bytes(32), "little") & ((1 << 253) - 1) for _ in range(60)]
    bits = np.zeros((len(scalars), 256), dtype=np.int32)
    for i, s in enumerate(scalars):
        bits[i] = np.unpackbits(
            np.frombuffer(s.to_bytes(32, "little"), dtype=np.uint8), bitorder="little"
        )
    dig = digits4_from_bits(bits.T)
    mag, neg = jax.jit(recode_signed4)(dig)
    mag = np.asarray(mag)
    neg = np.asarray(neg)
    assert mag.max() <= 8
    for i, s in enumerate(scalars):
        acc = 0
        for k in range(64):
            d = int(mag[k, i]) * (-1 if neg[k, i] else 1)
            acc += d * (16**k)
        assert acc == s, (i, s)


def test_vectorized_prepare_matches_per_item_reference():
    """The numpy-vectorized ``batch_verify.prepare`` must agree with a
    straightforward per-item reference on pre_ok and on every tensor row
    where pre_ok holds (rejected lanes are don't-care: the device bitmap
    is masked by pre_ok).  Coverage includes malformed lengths, the
    y >= p and S >= L canonicity boundaries, the x-parity bit, and
    random garbage."""
    import hashlib

    import numpy as np

    from mochi_tpu.crypto import batch_verify as bv, field as F, keys
    from mochi_tpu.verifier.spi import VerifyItem

    def prepare_ref(items):
        n = len(items)
        y_a = np.zeros((n, F.NLIMBS), np.int32)
        y_r = np.zeros((n, F.NLIMBS), np.int32)
        sign_a = np.zeros(n, np.int32)
        sign_r = np.zeros(n, np.int32)
        s_bits = np.zeros((n, 256), np.int32)
        h_bits = np.zeros((n, 256), np.int32)
        pre_ok = np.zeros(n, bool)
        for i, it in enumerate(items):
            if len(it.public_key) != 32 or len(it.signature) != 64:
                continue
            a = bytes(it.public_key)
            r = bytes(it.signature[:32])
            s = int.from_bytes(it.signature[32:], "little")
            ya = int.from_bytes(a, "little") & ((1 << 255) - 1)
            yr = int.from_bytes(r, "little") & ((1 << 255) - 1)
            if ya >= F.P_INT or yr >= F.P_INT or s >= F.L_INT:
                continue
            h = (
                int.from_bytes(
                    hashlib.sha512(r + a + bytes(it.message)).digest(), "little"
                )
                % F.L_INT
            )
            y_a[i] = F.int_to_limbs(ya)
            y_r[i] = F.int_to_limbs(yr)
            sign_a[i] = a[31] >> 7
            sign_r[i] = r[31] >> 7
            s_bits[i] = np.unpackbits(
                np.frombuffer(s.to_bytes(32, "little"), np.uint8),
                bitorder="little",
            )
            h_bits[i] = np.unpackbits(
                np.frombuffer(h.to_bytes(32, "little"), np.uint8),
                bitorder="little",
            )
            pre_ok[i] = True
        return y_a, sign_a, y_r, sign_r, s_bits, h_bits, pre_ok

    rng = np.random.default_rng(0xF00D)
    kp = keys.generate_keypair()
    P, L = F.P_INT, F.L_INT

    def enc(v, hi=0):
        return (v | (hi << 255)).to_bytes(32, "little")

    items = [
        VerifyItem(kp.public_key, b"m%d" % i, kp.sign(b"m%d" % i))
        for i in range(40)
    ]
    items += [
        VerifyItem(b"short", b"m", kp.sign(b"m")),
        VerifyItem(kp.public_key, b"m", b"tiny"),
        VerifyItem(b"", b"", b""),
    ]
    for ya in (P - 1, P, P + 1, (1 << 255) - 1, 0, 19):
        for hi in (0, 1):
            items.append(VerifyItem(enc(ya, hi), b"x", kp.sign(b"x")))
    for sval in (L - 1, L, L + 1, (1 << 256) - 1, 0):
        sig = kp.sign(b"y")[:32] + (sval % (1 << 256)).to_bytes(32, "little")
        items.append(VerifyItem(kp.public_key, b"y", sig))
    for yr in (P - 1, P, P + 19):
        items.append(
            VerifyItem(kp.public_key, b"z", enc(yr) + kp.sign(b"z")[32:])
        )
    for _ in range(60):
        items.append(VerifyItem(rng.bytes(32), rng.bytes(8), rng.bytes(64)))

    ref = prepare_ref(items)
    got = bv.prepare(items)
    assert np.array_equal(ref[6], got[6]), "pre_ok diverged"
    ok = ref[6]
    for k in range(6):
        assert np.array_equal(ref[k][ok], got[k][ok]), k


def test_field_loose_limb_invariant_under_random_op_chains():
    """Every field op must (a) keep limbs in [0, LOOSE] — the invariant the
    per-op bound proofs in field.py's docstrings rely on — and (b) agree
    with python-int arithmetic mod p.  Random 40-op chains over random
    loose inputs; any bound violation would be a latent int32-overflow
    seed in a later multiply."""
    import numpy as np

    import jax

    from mochi_tpu.crypto import field as F

    rng = np.random.default_rng(0x10053)
    B = 4
    lanes = (B,)

    def rand_loose():
        arr = rng.integers(0, F.LOOSE + 1, size=(F.NLIMBS, B)).astype(np.int32)
        vals = [
            sum(int(arr[i, j]) << (F.RADIX * i) for i in range(F.NLIMBS))
            for j in range(B)
        ]
        return arr, vals

    a, va = rand_loose()
    b, vb = rand_loose()
    ops = [
        ("add", lambda x, y: F.add(x, y), lambda u, v: u + v),
        ("sub", lambda x, y: F.sub(x, y), lambda u, v: u - v),
        ("mul", lambda x, y: F.mul(x, y), lambda u, v: u * v),
        ("square", lambda x, y: F.square(x), lambda u, v: u * u),
        ("neg", lambda x, y: F.neg(x), lambda u, v: -u),
        ("mul3", lambda x, y: F.mul_small(x, 3), lambda u, v: u * 3),
        ("mul121666", lambda x, y: F.mul_small(x, 486), lambda u, v: u * 486),
    ]
    for step in range(40):
        name, dev_op, int_op = ops[rng.integers(len(ops))]
        out = np.asarray(dev_op(a, b))
        assert (out >= 0).all() and (out <= F.LOOSE).all(), (
            step, name, int(out.min()), int(out.max()),
        )
        got = F.limbs_to_int_batch(np.asarray(jax.jit(F.canonical)(out)))
        want = [int_op(u, v) % F.P_INT for u, v in zip(va, vb)]
        assert got == want, (step, name)
        b, vb = a, va
        a, va = out, got


# ---------------------------------------------------------------------------
# Epoch exhaustion (paper procedure, mochiDB.tex:162-163): per-object epochs
# grow without bound — one epoch per committed write — so the protocol must
# stay EXACT past every representation boundary a long-lived deployment can
# cross: the float53 line (a single float contamination silently corrupts
# odd timestamps > 2^53) and the codec's varint byte-length boundaries up to
# the full uint64 range the wire format guarantees.


def _epoch_store_pair():
    from mochi_tpu.cluster import ClusterConfig
    from mochi_tpu.server.store import DataStore

    cfg = ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{8001 + i}" for i in range(4)}, rf=4
    )
    return [DataStore(f"server-{i}", cfg) for i in range(4)]


def _drive_epoch_rounds(base: int, seed: int, rounds: int) -> None:
    """Shared drive for the generative and deterministic epoch tests:
    grant issuance, codec roundtrip, commit and epoch advance/GC at a huge
    per-object epoch, every step checked bit-exact."""
    from mochi_tpu.protocol import (
        Action,
        Operation,
        Transaction,
        Write1OkFromServer,
        Write1ToServer,
        Write2AnsFromServer,
        Write2ToServer,
        WriteCertificate,
        transaction_hash,
    )
    from mochi_tpu.server.store import EPOCH_UNIT, GRANT_GC_EPOCHS

    stores = _epoch_store_pair()
    epoch = (base // EPOCH_UNIT) * EPOCH_UNIT
    key = "exhaust"
    for s in stores:
        s._get_or_create(key).current_epoch = epoch

    for r in range(rounds):
        txn = Transaction((Operation(Action.WRITE, key, b"v%d" % r),))
        blind = Transaction((Operation(Action.WRITE, key, None),))
        req = Write1ToServer("client-e", blind, seed, transaction_hash(txn))
        responses = [s.process_write1(req) for s in stores]
        assert all(isinstance(x, Write1OkFromServer) for x in responses)
        want_ts = epoch + seed  # exact python-int arithmetic, never float
        for x in responses:
            g = x.multi_grant.grants[key]
            assert g.timestamp == want_ts
            # a float anywhere in the path would round odd ts > 2^53
            assert isinstance(g.timestamp, int)
        wc = WriteCertificate(
            {x.multi_grant.server_id: x.multi_grant for x in responses}
        )

        # wire-exactness of the huge timestamps: python codec roundtrip,
        # and the C codec agrees byte-for-byte when available
        blob = _encode_py(wc.to_obj())
        assert WriteCertificate.from_obj(_decode_py(blob)).grants[
            stores[0].server_id
        ].grants[key].timestamp == want_ts
        if _native is not None:
            assert _native.encode(wc.to_obj()) == blob
            assert _native.decode(blob) == _decode_py(blob)

        answers = [s.process_write2(Write2ToServer(wc, txn)) for s in stores]
        for ans in answers:
            assert isinstance(ans, Write2AnsFromServer)
        epoch = (want_ts // EPOCH_UNIT) * EPOCH_UNIT + EPOCH_UNIT
        for s in stores:
            sv = s.data[key]
            assert sv.current_epoch == epoch  # exact advance, no drift
            # grant GC horizon arithmetic stays exact at huge epochs
            assert all(e >= epoch - GRANT_GC_EPOCHS for e in sv.grants)


@settings(max_examples=40, deadline=None)
@given(
    # epoch bases straddling float53, varint byte boundaries, and uint64
    base=st.one_of(
        st.integers(min_value=2**53 - 10_000, max_value=2**53 + 10_000),
        st.integers(min_value=2**56 - 10_000, max_value=2**56 + 10_000),
        st.integers(min_value=2**63 - 10_000, max_value=2**63 + 10_000),
        st.integers(min_value=0, max_value=2**64 - 2_000_000),
    ),
    seed=st.integers(min_value=0, max_value=999),
    rounds=st.integers(min_value=1, max_value=3),
)
def test_epochs_past_2_53_stay_exact(base, seed, rounds):
    """Grant issuance, epoch advance, grant GC and the wire codec must be
    bit-exact when per-object epochs exceed 2^53 (and up to uint64)."""
    _drive_epoch_rounds(base, seed, rounds)


@pytest.mark.parametrize(
    "base",
    [
        2**53 - 1_000,  # last fully float-exact epoch
        2**53 + 1,      # first odd value a float path would corrupt
        2**53 + 999,
        2**56 - 5,      # varint 8->9 byte boundary region
        2**56 + 123,
        2**63 - 7,      # int64 sign boundary (a C codec's danger zone)
        2**63 + 1_001,
        2**64 - 2_000_000,  # near the wire format's uint64 ceiling
    ],
)
def test_epochs_boundary_sweep_deterministic(base):
    """Hypothesis-free pinned sweep of the same drive at every
    representation boundary, so the property holds on bare CI images too
    (the paper's epoch-exhaustion procedure, mochiDB.tex:162-163)."""
    _drive_epoch_rounds(base, seed=777, rounds=2)

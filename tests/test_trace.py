"""Round-15 causal tracing: context propagation, cost cards, ring bounds,
always-sample-on-conviction, flight recorder, and the merge CLI.

The cross-PROCESS propagation test (client → 2 server processes → merged
connected span tree) lives here too, driving the real ``ProcessCluster``
spawn/drain lifecycle: replicas dump their rings to ``MOCHI_TRACE_DIR`` on
the SIGTERM drain path, and the merge joins them with the client's ring.
"""

from __future__ import annotations

import asyncio
import glob
import json
import os
import time

import pytest

from mochi_tpu.client.txn import TransactionBuilder
from mochi_tpu.obs import trace as T


def _all_events(vc, clients):
    evs = []
    for c in clients:
        evs.extend(c.tracer.events())
    for r in vc.replicas:
        evs.extend(r.tracer.events())
    return evs


# ------------------------------------------------------------------- core


def test_mint_sampling_is_seeded_and_head_based():
    a = T.Tracer("p", sample_rate=0.5, seed=123)
    b = T.Tracer("p", sample_rate=0.5, seed=123)
    va = [a.mint().sampled for _ in range(64)]
    vb = [b.mint().sampled for _ in range(64)]
    assert va == vb, "same seed + label must give the same sampling stream"
    assert 0 < sum(va) < 64, "rate 0.5 should sample some and skip some"
    off = T.Tracer("p", sample_rate=0.0)
    assert off.mint() is None and not off.enabled


def test_record_skips_unsampled_and_force_upgrades():
    tr = T.Tracer("p", sample_rate=1.0, seed=1)
    ctx = tr.mint()
    unsampled = T.TraceContext("aa" * 8, "bb" * 8, None, sampled=False)
    assert tr.record("x", unsampled, time.time(), 0.001) is None
    assert tr.record("x", None, time.time(), 0.001) is None
    assert len(tr.ring) == 0
    # forced: records with forced=True even for unsampled/absent contexts
    assert tr.force_mark("err", unsampled) is not None
    assert tr.force_mark("err", None) is not None
    assert all(ev["args"]["forced"] for ev in tr.ring)
    assert tr.spans_forced == 2
    # sampled context records plainly
    sid = tr.record("ok", ctx, time.time(), 0.002, args={"rtt": 1})
    assert sid is not None and tr.ring[-1]["args"]["parent_id"] == ctx.span_id


def test_wire_roundtrip_and_malformed_tolerance():
    ctx = T.TraceContext("ab" * 8, "cd" * 8, None, sampled=True)
    back = T.TraceContext.from_wire(ctx.to_wire())
    assert back.trace_id == ctx.trace_id and back.span_id == ctx.span_id
    assert back.sampled
    for junk in ((), (b"", b"x", 1), ("a", "b", 1), (b"x" * 99, b"y", 1),
                 (b"x", b"y", "z"), None, 42):
        assert T.TraceContext.from_wire(junk) is None


def test_ring_is_bounded_under_openloop_shaped_burst():
    """Config-9 shape in miniature: far more span traffic than the ring
    holds — memory stays O(ring), newest evidence wins."""
    tr = T.Tracer("p", sample_rate=1.0, seed=7, ring=128)
    for i in range(10_000):
        ctx = tr.mint()
        tr.record("burst", ctx, time.time(), 0.0001, args={"i": i})
    assert len(tr.ring) == 128
    assert tr.spans_recorded == 10_000
    # oldest aged out, newest retained
    kept = [ev["args"]["i"] for ev in tr.ring]
    assert min(kept) == 10_000 - 128 and max(kept) == 9_999


def test_cost_cards_and_tree_connectivity():
    tr = T.Tracer("client", sample_rate=1.0, seed=3)
    ctx = tr.mint()
    t0 = time.time()
    tr.record("txn.write", ctx, t0, 0.05, span_id=ctx.span_id)
    tr.record("client.fanout", ctx, t0, 0.01,
              args={"rtt": 1, "wire_bytes": 512})
    remote = T.Tracer("replica", sample_rate=1.0, seed=4)
    # the remote side parents under the client's span (wire propagation)
    rctx = T.TraceContext.from_wire(ctx.to_wire())
    remote.record("replica.handle_batch", rctx, t0, 0.004,
                  args={"verify_items": 3, "verify_unique": 2,
                        "verify_memoized": 1, "queue_us": 120.0})
    evs = T.merge_events([tr.export_chrome(), remote.export_chrome()])
    cards = T.cost_cards(evs)
    card = cards[ctx.trace_id]
    assert card["rtt"] == 1 and card["wire_bytes"] == 512
    assert card["verify_items"] == 3
    assert card["verify_unique"] == 2 and card["verify_memoized"] == 1
    assert card["queue_us"] == 120.0
    assert set(card["stages_us"]) == {
        "txn.write", "client.fanout", "replica.handle_batch"
    }
    assert T.span_tree_connected(evs, ctx.trace_id)
    # an orphan (parent never recorded) breaks connectivity
    orphan = T.TraceContext(ctx.trace_id, "99" * 8, None, True)
    lone = T.Tracer("x", sample_rate=1.0)
    lone.record("dangling", orphan.child(lone.new_span_id()), t0, 0.001)
    assert not T.span_tree_connected(
        evs + lone.events(), ctx.trace_id
    )


# --------------------------------------------------------- in-process e2e


def test_cluster_trace_end_to_end(monkeypatch):
    monkeypatch.setenv("MOCHI_TRACE_SAMPLE", "1.0")
    monkeypatch.setenv("MOCHI_TRACE_SEED", "11")
    asyncio.run(asyncio.wait_for(_cluster_main(), timeout=60))


async def _cluster_main():
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async with VirtualCluster(4, rf=4) as vc:
        client = vc.client()
        await client.execute_write_transaction(
            TransactionBuilder().write("tr-k", b"v").build()
        )
        res = await client.execute_read_transaction(
            TransactionBuilder().read("tr-k").build()
        )
        assert bytes(res.operations[0].value) == b"v"
        evs = _all_events(vc, [client])
        cards = T.cost_cards(evs)
        writes = [
            (tid, c) for tid, c in cards.items() if "txn.write" in c["stages_us"]
        ]
        reads = [
            (tid, c) for tid, c in cards.items() if "txn.read" in c["stages_us"]
        ]
        assert len(writes) == 1 and len(reads) == 1
        tid, card = writes[0]
        # the write's span tree is CONNECTED across client + all replicas
        assert T.span_tree_connected(evs, tid)
        assert any(p.startswith("client:") for p in card["processes"])
        assert sum(p.startswith("replica:") for p in card["processes"]) == 4
        # the cost card carries the tentpole's ledger: 2 RTTs (write1 +
        # write2 fan-outs), wire bytes, verify items with the unique/
        # memoized split, store apply + queue wait
        assert card["rtt"] == 2
        assert card["wire_bytes"] > 0
        assert card["verify_items"] > 0
        assert card["verify_unique"] + card["verify_memoized"] > 0
        assert "store.write1-apply" in card["stages_us"]
        assert "store.write2-apply" in card["stages_us"]
        for stage in ("write1-phase", "write2-fanout-wait", "write2-tally"):
            assert stage in card["stages_us"], card["stages_us"]
        # reads: 1 RTT, no verifies (MAC'd inline path)
        rtid, rcard = reads[0]
        assert rcard["rtt"] == 1 and rcard["verify_items"] == 0
        assert T.span_tree_connected(evs, rtid)


def test_unsampled_traffic_keeps_untraced_wire(monkeypatch):
    """sample_rate=0: no contexts mint, envelopes carry no trace field and
    no spans record anywhere — the zero-overhead posture."""
    monkeypatch.delenv("MOCHI_TRACE", raising=False)
    monkeypatch.delenv("MOCHI_TRACE_SAMPLE", raising=False)
    asyncio.run(asyncio.wait_for(_unsampled_main(), timeout=60))


async def _unsampled_main():
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async with VirtualCluster(4, rf=4) as vc:
        client = vc.client()
        await client.execute_write_transaction(
            TransactionBuilder().write("tr-u", b"v").build()
        )
        assert not client.tracer.enabled
        assert _all_events(vc, [client]) == []


# ------------------------------------------------- conviction flight path


def test_forge_cert_conviction_produces_connected_flight_dump(
    tmp_path, monkeypatch
):
    """The acceptance scenario: a forged certificate reaching a replica is
    convicted (BAD_CERTIFICATE), and the flight-recorder dump + client ring
    merge into a span tree containing the convicted message's path from
    client send to replica verdict."""
    monkeypatch.setenv("MOCHI_TRACE_SAMPLE", "1.0")
    monkeypatch.setenv("MOCHI_TRACE_SEED", "13")
    monkeypatch.setenv("MOCHI_TRACE_DIR", str(tmp_path))
    asyncio.run(asyncio.wait_for(_conviction_main(tmp_path), timeout=60))


async def _conviction_main(tmp_path):
    from mochi_tpu.client.txn import TxnTrace
    from mochi_tpu.protocol import (
        FailType, RequestFailedFromServer, Write2ToServer, WriteCertificate,
    )
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async with VirtualCluster(4, rf=4) as vc:
        client = vc.client()
        txn = TransactionBuilder().write("fc-k", b"v").build()
        await client.execute_write_transaction(txn)
        # the committed certificate with every grant signature forged —
        # the config-10 forge-cert leg distilled to the seam that convicts
        sv = vc.replicas[0].store._get("fc-k")
        forged = WriteCertificate(
            {
                sid: mg.with_signature(b"\x00" * 64)
                for sid, mg in sv.current_certificate.grants.items()
            }
        )
        with TxnTrace(client.tracer, "txn.write") as tt:
            with tt.stage("write2-fanout-wait"):
                responses = await client._fan_out(
                    txn, lambda: Write2ToServer(forged, txn)
                )
        assert responses, "replicas must answer the forged Write2"
        assert all(
            isinstance(p, RequestFailedFromServer)
            and p.fail_type == FailType.BAD_CERTIFICATE
            for p in responses.values()
        ), responses
        dumps = sorted(glob.glob(os.path.join(str(tmp_path), "flight-*.json")))
        assert dumps, "conviction must drive the flight recorder to disk"
        docs = [json.load(open(p)) for p in dumps]
        assert any(d["reason"] == "bad-certificate" for d in docs)
        evs = T.merge_events(docs)
        evs.extend(client.tracer.events())
        for r in vc.replicas:
            evs.extend(r.tracer.events())
        convictions = [
            ev for ev in evs if ev["name"] == "replica.conviction"
        ]
        assert convictions
        # the conviction is attributed to the client's transaction, and the
        # span tree is connected from the client's send to the verdict
        tid = tt.ctx.trace_id
        attributed = [
            ev for ev in convictions if ev["args"].get("trace_id") == tid
        ]
        assert attributed, "traced Write2 must attribute its conviction"
        assert T.span_tree_connected(evs, tid)
        names = {
            ev["name"]
            for ev in evs
            if ev["args"].get("trace_id") == tid
        }
        # client send side ... replica verdict side, one connected trace
        assert "client.fanout" in names and "replica.conviction" in names
        assert "write2-fanout-wait" in names and "txn.write" in names


def test_conviction_dumps_even_when_head_unsampled(tmp_path, monkeypatch):
    """always-sample-on-conviction: with tracing effectively off for this
    client (rate 0 → no wire context), a convicted certificate still
    force-records a verdict span and dumps the flight ring."""
    monkeypatch.delenv("MOCHI_TRACE", raising=False)
    monkeypatch.delenv("MOCHI_TRACE_SAMPLE", raising=False)
    monkeypatch.setenv("MOCHI_TRACE_DIR", str(tmp_path))
    asyncio.run(asyncio.wait_for(_unsampled_conviction(tmp_path), timeout=60))


async def _unsampled_conviction(tmp_path):
    from mochi_tpu.protocol import (
        FailType, RequestFailedFromServer, Write2ToServer, WriteCertificate,
    )
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async with VirtualCluster(4, rf=4) as vc:
        client = vc.client()
        assert not client.tracer.enabled  # head sampling is OFF
        txn = TransactionBuilder().write("fc-u", b"v").build()
        await client.execute_write_transaction(txn)
        sv = vc.replicas[0].store._get("fc-u")
        forged = WriteCertificate(
            {
                sid: mg.with_signature(b"\x00" * 64)
                for sid, mg in sv.current_certificate.grants.items()
            }
        )
        responses = await client._fan_out(
            txn, lambda: Write2ToServer(forged, txn)
        )
        assert all(
            isinstance(p, RequestFailedFromServer)
            and p.fail_type == FailType.BAD_CERTIFICATE
            for p in responses.values()
        )
        dumps = glob.glob(os.path.join(str(tmp_path), "flight-*.json"))
        assert dumps
        docs = [json.load(open(p)) for p in dumps]
        assert any(d["reason"] == "bad-certificate" for d in docs)
        forced = [
            ev
            for ev in T.merge_events(docs)
            if ev["name"] == "replica.conviction" and ev["args"].get("forced")
        ]
        assert forced, "unsampled conviction must still force-record"


def test_conviction_flight_dumps_are_bounded(tmp_path, monkeypatch):
    """A forged-cert FLOOD must buy bounded disk: past CONVICTION_DUMPS_MAX
    per reason, convictions still force-record spans but stop writing
    full-ring dumps."""
    monkeypatch.setenv("MOCHI_TRACE_DIR", str(tmp_path))
    asyncio.run(asyncio.wait_for(_dump_bound_main(tmp_path), timeout=60))


async def _dump_bound_main(tmp_path):
    from mochi_tpu.server.replica import CONVICTION_DUMPS_MAX
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async with VirtualCluster(4, rf=4) as vc:
        r = vc.replicas[0]
        for i in range(CONVICTION_DUMPS_MAX * 3):
            r._convict("bad-certificate", None, {"i": i})
        dumps = glob.glob(os.path.join(str(tmp_path), "flight-*.json"))
        assert len(dumps) == CONVICTION_DUMPS_MAX, len(dumps)
        # every conviction still recorded a (cheap) forced span
        marks = [
            ev for ev in r.tracer.events() if ev["name"] == "replica.conviction"
        ]
        assert len(marks) == CONVICTION_DUMPS_MAX * 3


# ------------------------------------------------------ cross-process e2e


def test_cross_process_trace_merges_into_one_tree(tmp_path, monkeypatch):
    monkeypatch.setenv("MOCHI_TRACE_SAMPLE", "1.0")
    monkeypatch.setenv("MOCHI_TRACE_SEED", "17")
    asyncio.run(asyncio.wait_for(_procs_main(tmp_path), timeout=120))


async def _procs_main(tmp_path):
    from mochi_tpu.testing.process_cluster import ProcessCluster

    flight_dir = os.path.join(str(tmp_path), "flight")
    pc = ProcessCluster(
        4,
        rf=4,
        n_processes=2,
        env={
            "MOCHI_TRACE_SAMPLE": "1.0",
            "MOCHI_TRACE_SEED": "17",
            "MOCHI_TRACE_DIR": flight_dir,
        },
    )
    async with pc:
        client = pc.client()
        # one txn spanning both server processes (rf=4 of 4 servers: the
        # replica set covers every shard, hosted 2 per process)
        await client.execute_write_transaction(
            TransactionBuilder().write("xp-a", b"1").write("xp-b", b"2").build()
        )
        client_events = client.tracer.events()
        assert client_events
    # the SIGTERM drain dumped each replica's ring (server/__main__ →
    # MochiReplica.drain → flight dump) — merge them with the client ring
    dumps = sorted(glob.glob(os.path.join(flight_dir, "flight-*.json")))
    assert len(dumps) >= 2, dumps
    docs = [json.load(open(p)) for p in dumps]
    assert all(d["reason"] == "drain" for d in docs)
    evs = T.merge_events(docs) + client_events
    cards = T.cost_cards(evs)
    writes = {
        tid: c for tid, c in cards.items() if "txn.write" in c["stages_us"]
    }
    assert len(writes) == 1
    tid, card = next(iter(writes.items()))
    assert T.span_tree_connected(evs, tid), card
    replica_procs = {p for p in card["processes"] if p.startswith("replica:")}
    assert len(replica_procs) == 4, card["processes"]
    assert any(p.startswith("client:") for p in card["processes"])
    assert card["rtt"] == 2 and card["verify_items"] > 0


# ----------------------------------------------------------------- tools


def test_trace_merge_cli(tmp_path, capsys):
    from mochi_tpu.tools.trace import main

    a = T.Tracer("client", sample_rate=1.0, seed=5)
    ctx = a.mint()
    a.record("txn.write", ctx, time.time(), 0.01, span_id=ctx.span_id)
    b = T.Tracer("replica", sample_rate=1.0, seed=6)
    b.record(
        "replica.handle_batch",
        T.TraceContext.from_wire(ctx.to_wire()),
        time.time(),
        0.002,
        args={"verify_items": 2},
    )
    pa = os.path.join(str(tmp_path), "a.json")
    pb = os.path.join(str(tmp_path), "b.json")
    a.dump_flight("test", path=pa)
    b.dump_flight("test", path=pb)

    assert main([pa, pb]) == 0
    merged = json.loads(capsys.readouterr().out)
    assert len(merged["traceEvents"]) == 2
    assert merged["otherData"]["traces"] == 1

    out = os.path.join(str(tmp_path), "cards.json")
    assert main([pa, pb, "--cards", "-o", out]) == 0
    cards = json.load(open(out))
    assert cards[ctx.trace_id]["verify_items"] == 2
    assert cards[ctx.trace_id]["connected"] is True

    # --trace-id filters to one transaction
    assert main([pa, pb, "--trace-id", "ffffffffffffffff"]) == 0
    empty = json.loads(capsys.readouterr().out)
    assert empty["traceEvents"] == []

    # unreadable input fails typed
    assert main([os.path.join(str(tmp_path), "missing.json")]) == 2


def test_global_summary_is_always_nonempty():
    s = T.global_summary()
    assert isinstance(s, dict) and s
    for k in ("enabled", "sample_rate", "spans_recorded", "traces_started"):
        assert k in s

"""Known-signer comb verification (crypto/comb.py): differential contract.

The comb path must produce bit-for-bit the same verdicts as OpenSSL and as
the general ladder path, for valid signatures, forgeries, wrong-key and
malformed inputs, and mixed registered/unregistered batches — the same
contract ``tests/test_crypto_jax.py`` enforces for the general path.
"""

from __future__ import annotations

import numpy as np
import pytest

from mochi_tpu.crypto import batch_verify, comb, keys
from mochi_tpu.verifier.spi import VerifyItem


@pytest.fixture(scope="module")
def signers():
    return [keys.generate_keypair() for _ in range(5)]


@pytest.fixture(scope="module")
def registry(signers):
    reg = comb.SignerRegistry()
    for kp in signers:
        assert reg.register(kp.public_key) is not None
    return reg


def _expected(items):
    return [keys.verify(it.public_key, it.message, it.signature) for it in items]


# ---------------------------------------------------------------- registry


def test_register_rejects_invalid_encodings(registry):
    # non-canonical y (>= p): the encoding of p itself
    p_enc = ((1 << 255) - 19).to_bytes(32, "little")
    assert comb.SignerRegistry().register(p_enc) is None
    # not a curve point: some small y has no valid x; the registry must
    # reject exactly those the RFC 8032 decode rejects
    non_point = next(
        y
        for y in range(2, 64)
        if comb.decompress_host(y.to_bytes(32, "little")) is None
    )
    assert comb.SignerRegistry().register(non_point.to_bytes(32, "little")) is None
    # wrong length
    assert comb.SignerRegistry().register(b"\x00" * 31) is None
    # x = 0 with sign bit set: y = 1 encoding with bit 255
    bad = bytearray((1).to_bytes(32, "little"))
    bad[31] |= 0x80
    assert comb.SignerRegistry().register(bytes(bad)) is None


def test_register_is_idempotent_and_indexes_stable(signers, registry):
    for i, kp in enumerate(signers):
        assert registry.index_of(kp.public_key) == i
        assert registry.register(kp.public_key) == i
    assert len(registry) == len(signers)


def test_decompress_host_matches_device_decode(signers):
    # registration's host decode accepts exactly the keys the device path
    # accepts (spot check: all generated pubkeys round-trip)
    for kp in signers:
        aff = comb.decompress_host(kp.public_key)
        assert aff is not None
        x, y = aff
        # parity bit must match bit 255 of the encoding
        assert (x & 1) == (kp.public_key[31] >> 7)


# ---------------------------------------------------------------- verdicts


def _mixed_items(signers, n=64):
    """Valid + forged + wrong-key + malformed items from registered keys."""
    items, kinds = [], []
    for i in range(n):
        kp = signers[i % len(signers)]
        msg = b"comb-msg-%d" % i
        sig = kp.sign(msg)
        kind = i % 8
        if kind == 3:  # flip a signature bit (R half)
            sig = sig[:5] + bytes([sig[5] ^ 0x40]) + sig[6:]
        elif kind == 5:  # flip an S bit
            sig = sig[:40] + bytes([sig[40] ^ 1]) + sig[41:]
        elif kind == 6:  # sign with a different registered key
            sig = signers[(i + 1) % len(signers)].sign(msg)
            msg = b"comb-msg-%d" % i  # verify against kp's pubkey
        elif kind == 7:  # non-canonical S (S + L)
            s_int = int.from_bytes(sig[32:], "little")
            from mochi_tpu.crypto import field as F

            s2 = s_int + F.L_INT
            if s2 < (1 << 256):
                sig = sig[:32] + s2.to_bytes(32, "little")
        items.append(VerifyItem(kp.public_key, msg, sig))
        kinds.append(kind)
    return items


@pytest.mark.slow
def test_comb_matches_openssl_and_ladder(signers, registry):
    items = _mixed_items(signers)
    expect = _expected(items)
    got_comb = batch_verify.verify_batch(items, registry=registry)
    got_ladder = batch_verify.verify_batch(items)
    assert got_comb == expect
    assert got_ladder == expect
    assert any(expect) and not all(expect)  # the mix is non-trivial


@pytest.mark.slow
def test_mixed_registered_and_unregistered(signers, registry):
    stranger = keys.generate_keypair()  # never registered
    items = []
    for i in range(24):
        kp = signers[i % 3] if i % 2 == 0 else stranger
        msg = b"mix-%d" % i
        sig = kp.sign(msg) if i % 5 else kp.sign(b"other")
        items.append(VerifyItem(kp.public_key, msg, sig))
    expect = _expected(items)
    got = batch_verify.verify_batch(items, registry=registry)
    assert got == expect


def test_comb_disabled_by_env(monkeypatch, signers, registry):
    monkeypatch.setenv("MOCHI_COMB", "0")
    kp = signers[0]
    items = [VerifyItem(kp.public_key, b"x", kp.sign(b"x"))]
    assert batch_verify.verify_batch(items, registry=registry) == [True]


def test_empty_registry_routes_general(signers):
    reg = comb.SignerRegistry()
    kp = signers[0]
    items = [VerifyItem(kp.public_key, b"y", kp.sign(b"y"))]
    assert batch_verify.verify_batch(items, registry=reg) == [True]


def test_malformed_lengths_rejected(signers, registry):
    kp = signers[0]
    items = [
        VerifyItem(kp.public_key, b"m", kp.sign(b"m")[:63]),  # short sig
        VerifyItem(kp.public_key[:31], b"m", kp.sign(b"m")),  # short key
        VerifyItem(kp.public_key, b"m", kp.sign(b"m")),
    ]
    got = batch_verify.verify_batch(items, registry=registry)
    assert got == [False, False, True]


def test_noncanonical_r_rejected(signers, registry):
    # R encoding >= p: host precheck rejects on both paths identically
    kp = signers[0]
    sig = bytearray(kp.sign(b"m"))
    sig[:32] = ((1 << 255) - 19).to_bytes(32, "little")
    items = [VerifyItem(kp.public_key, b"m", bytes(sig))]
    assert batch_verify.verify_batch(items, registry=registry) == [False]
    assert batch_verify.verify_batch(items) == [False]


@pytest.mark.slow
def test_registry_growth_across_capacity_boundary():
    # capacity pads to powers of two (min 8): crossing 8 -> 16 must
    # invalidate the cached device table and keep verdicts correct
    kps = [keys.generate_keypair() for _ in range(10)]
    reg = comb.SignerRegistry()
    for kp in kps[:8]:
        reg.register(kp.public_key)
    items = [VerifyItem(kps[0].public_key, b"a", kps[0].sign(b"a"))]
    assert batch_verify.verify_batch(items, registry=reg) == [True]
    for kp in kps[8:]:
        reg.register(kp.public_key)
    items = [
        VerifyItem(kp.public_key, b"b%d" % i, kp.sign(b"b%d" % i))
        for i, kp in enumerate(kps)
    ]
    assert batch_verify.verify_batch(items, registry=reg) == [True] * len(kps)


def test_backend_with_registry_warmup_and_call(signers, registry):
    backend = batch_verify.JaxBatchBackend(
        min_device_items=0, registry=registry
    )
    backend.warmup([16])
    items = _mixed_items(signers, n=20)
    assert list(backend(items)) == _expected(items)


def test_backend_gating_never_stalls_on_registry_growth(signers):
    """Registration growth must not park live traffic behind a comb
    recompile: already-registered signers KEEP comb service at the pinned
    older generation (their table rows are stable), the NEW signer rides
    the general ladder until the background re-warm lands, and verdicts
    stay correct throughout."""
    import time

    reg = comb.SignerRegistry()
    reg.register_all([kp.public_key for kp in signers[:2]])
    backend = batch_verify.JaxBatchBackend(min_device_items=0, registry=reg)
    backend.warmup([16])
    kp = signers[0]
    items = [VerifyItem(kp.public_key, b"g1", kp.sign(b"g1"))] * 4

    before = comb.comb_dispatch_count()
    assert list(backend(items)) == [True] * 4
    assert comb.comb_dispatch_count() > before  # comb path live
    pinned = backend._comb_pinned_gen(16)
    assert pinned == 2

    # grow the registry: old signers keep comb at the pinned generation
    grower = keys.generate_keypair()
    assert reg.register(grower.public_key) is not None
    before = comb.comb_dispatch_count()
    assert list(backend(items)) == [True] * 4
    assert comb.comb_dispatch_count() > before  # still comb, no stall

    # the NEW signer verifies correctly right away (general ladder)
    new_items = [VerifyItem(grower.public_key, b"g2", grower.sign(b"g2"))] * 4
    assert list(backend(new_items)) == [True] * 4

    # the growth kicked a background re-warm; the new signer joins comb
    deadline = time.time() + 120
    while time.time() < deadline:
        if backend._comb_pinned_gen(16) == 3:
            break
        time.sleep(0.5)
    assert backend._comb_pinned_gen(16) == 3, "comb never re-warmed"
    before = comb.comb_dispatch_count()
    assert list(backend(new_items)) == [True] * 4
    assert comb.comb_dispatch_count() > before


def test_comb_only_service_chunks_at_comb_buckets(signers):
    """A registered-signer-only service with no boot warmup never
    populates the general ready set; a new batch size must still be
    served via the already-compiled comb buckets (chunked), not a
    synchronous compile of the new shape."""
    reg = comb.SignerRegistry()
    reg.register_all([kp.public_key for kp in signers])
    backend = batch_verify.JaxBatchBackend(min_device_items=0, registry=reg)
    kp = signers[0]
    small = [VerifyItem(kp.public_key, b"c%d" % i, kp.sign(b"c%d" % i)) for i in range(8)]
    assert list(backend(small)) == [True] * 8  # first call: comb compiles (bucket 16)
    assert backend._comb_pinned_gen(16) is not None
    assert 16 not in backend._ready  # no general dispatch ever happened

    # larger batch, new natural bucket (32): served by chunking at the
    # compiled comb bucket 16
    big = [VerifyItem(kp.public_key, b"d%d" % i, kp.sign(b"d%d" % i)) for i in range(20)]
    before = comb.comb_dispatch_count()
    assert list(backend(big)) == [True] * 20
    assert comb.comb_dispatch_count() - before == 2  # two 16-sized chunks
    assert backend._comb_pinned_gen(32) is None  # not synchronously compiled


@pytest.mark.slow
def test_sharded_comb_matches_openssl_on_cpu_mesh(signers):
    """Sharded comb (shard_map over the 8-device CPU mesh, table
    replicated) produces the same bitmap as OpenSSL — the config-5 /
    multi-chip production posture."""
    from mochi_tpu.verifier.tpu import ShardedJaxBatchBackend

    backend = ShardedJaxBatchBackend(min_device_items=0)
    backend.register_signers([kp.public_key for kp in signers])
    assert backend.n_devices > 1  # conftest forces the 8-device CPU mesh
    items = _mixed_items(signers, n=40)
    expect = _expected(items)
    assert list(backend(items)) == expect
    # comb program actually dispatched (all signers registered)
    before = comb.comb_dispatch_count()
    assert list(backend(items)) == expect
    assert comb.comb_dispatch_count() > before

    # a mixed batch with an unregistered signer runs the general sharded
    # program whole (all-or-nothing routing) — verdicts still exact
    stranger = keys.generate_keypair()
    mixed = items[:6] + [VerifyItem(stranger.public_key, b"s", stranger.sign(b"s"))]
    before = comb.comb_dispatch_count()
    assert list(backend(mixed)) == _expected(mixed)
    assert comb.comb_dispatch_count() == before


def test_cluster_protocol_over_comb_verifier():
    """Full BFT protocol with every replica's verification routed through
    the comb-backed device backend (registry = the cluster's own replica
    identities + its clients): honest transactions commit, a forged
    MultiGrant from an attacker key is dropped at the verify seam, and the
    honest quorum still commits — the cluster-level contract of
    test_byzantine.py, now on the comb fast path."""
    import asyncio
    from dataclasses import replace

    from mochi_tpu.client import TransactionBuilder
    from mochi_tpu.protocol import (
        Write2AnsFromServer,
        Write2ToServer,
        WriteCertificate,
    )
    from mochi_tpu.testing import VirtualCluster
    from mochi_tpu.verifier.spi import BatchingVerifier

    registry = comb.SignerRegistry()
    backends = []

    def factory():
        b = batch_verify.JaxBatchBackend(min_device_items=0, registry=registry)
        backends.append(b)
        return BatchingVerifier(backend=b, max_delay_s=0.001)

    async def main():
        async with VirtualCluster(4, rf=4, verifier_factory=factory) as vc:
            registry.register_all(vc.config.public_keys.values())
            client = vc.client()
            registry.register(client.keypair.public_key)

            # honest write commits through the comb-routed verify seam
            await client.execute_write_transaction(
                TransactionBuilder().write("ck", "cv").build()
            )
            r = await client.execute_read_transaction(
                TransactionBuilder().read("ck").build()
            )
            assert r.operations[0].value == b"cv"

            # forged MultiGrant (attacker key, NOT registered): dropped at
            # the verify seam, honest quorum still commits
            from tests.test_byzantine import write1_via_wire

            txn = TransactionBuilder().write("ck2", b"honest").build()
            grants = await write1_via_wire(vc, client, txn)
            attacker = keys.generate_keypair()
            victim = sorted(grants)[0]
            forged = replace(grants[victim], signature=None)
            forged = forged.with_signature(attacker.sign(forged.signing_bytes()))
            wc = WriteCertificate({**grants, victim: forged})
            env = client._envelope(Write2ToServer(wc, txn), "w2-comb-forged")
            tid = sorted(vc.config.servers)[1]
            resp = await client.pool.send_and_receive(vc.config.servers[tid], env)
            # 3 honest grants remain = quorum for rf=4 -> commit succeeds on
            # the target replica, with the forged grant detected + dropped
            assert isinstance(resp.payload, Write2AnsFromServer)
            assert resp.payload.result.operations[0].value == b"honest"
            assert (
                vc.replica(tid).metrics.counters.get("replica.dropped-grants", 0)
                == 1
            )

    dispatches_before = comb.comb_dispatch_count()
    asyncio.run(asyncio.wait_for(main(), timeout=300))
    # the comb program really carried traffic in this cluster
    assert comb.comb_dispatch_count() > dispatches_before
    assert any(b._ready_comb for b in backends)


@pytest.mark.slow
def test_tree_impl_matches_chain_and_openssl(signers, registry):
    """The tree accumulation (MOCHI_COMB_IMPL=tree: one-hot MXU select +
    balanced reduction) must produce bit-identical verdicts to the chain
    form and OpenSSL on the adversarial mix."""
    items = _mixed_items(signers, n=32)
    expect = _expected(items)
    key_idx = np.asarray(
        [registry.index_of(it.public_key) for it in items], np.int32
    )
    (ckey, y_r, sign_r, s_sc, h_sc), pre_ok = comb._prepare_comb(
        items, key_idx, None
    )
    table = registry.device_table()
    chain = np.asarray(
        comb._verify_comb_jit(table, ckey, y_r, sign_r, s_sc, h_sc, impl="chain")
    )
    tree = np.asarray(
        comb._verify_comb_jit(table, ckey, y_r, sign_r, s_sc, h_sc, impl="tree")
    )
    np.testing.assert_array_equal(chain, tree)
    got = [bool(b) for b in np.logical_and(tree[: len(items)], pre_ok)]
    assert got == expect


def test_comb_chunked_pipeline_path(monkeypatch, signers, registry):
    """Oversized comb batches chunk at MAX_BUCKET behind the bounded
    launch window (verify_stream's pipelined path) — shrunk via
    monkeypatch so the CPU test exercises the real chunk/prepare-thread
    machinery without 8192-lane compiles."""
    monkeypatch.setattr(batch_verify, "MAX_BUCKET", 32)
    kp = signers[0]
    items = []
    for i in range(5 * 32 + 7):  # 5 full chunks + a ragged tail
        msg = b"chunk-%d" % i
        sig = kp.sign(msg)
        if i % 11 == 3:
            sig = sig[:8] + bytes([sig[8] ^ 2]) + sig[9:]
        items.append(VerifyItem(kp.public_key, msg, sig))
    expect = _expected(items)
    assert batch_verify.verify_batch(items, registry=registry) == expect


@pytest.mark.slow
def test_comb_randomized_mutation_fuzz(signers, registry):
    """Batched randomized differential fuzz: random byte flips at random
    positions in signature/pubkey/message, random message lengths, random
    registered/unregistered signers — one device launch, every verdict
    bit-compared against OpenSSL on BOTH the comb-routed and general
    paths.  Seed printed for reproduction."""
    import os as _os

    seed = int.from_bytes(_os.urandom(4), "little")
    print(f"fuzz seed: {seed}")
    rng = np.random.default_rng(seed)
    stranger = keys.generate_keypair()
    pool = signers + [stranger]
    items = []
    for i in range(96):
        kp = pool[int(rng.integers(0, len(pool)))]
        msg = bytes(rng.integers(0, 256, size=int(rng.integers(0, 200)), dtype=np.uint8))
        sig = bytearray(kp.sign(msg))
        pub = bytearray(kp.public_key)
        mutation = int(rng.integers(0, 4))
        if mutation == 1:  # flip a random signature bit
            pos = int(rng.integers(0, 64))
            sig[pos] ^= 1 << int(rng.integers(0, 8))
        elif mutation == 2:  # flip a random pubkey bit (may un-register it)
            pos = int(rng.integers(0, 32))
            pub[pos] ^= 1 << int(rng.integers(0, 8))
        elif mutation == 3:  # tamper the message after signing
            if msg:
                mpos = int(rng.integers(0, len(msg)))
                msg = msg[:mpos] + bytes([msg[mpos] ^ 0x10]) + msg[mpos + 1:]
        items.append(VerifyItem(bytes(pub), msg, bytes(sig)))
    expect = _expected(items)
    assert batch_verify.verify_batch(items, registry=registry) == expect, seed
    assert batch_verify.verify_batch(items) == expect, seed


def test_comb_table_math_against_host_ints(signers):
    """The device comb table rows really are [d*16^w](-A) in Niels form:
    rebuild one entry from host ints and compare limbs."""
    from mochi_tpu.crypto import field as F

    kp = signers[0]
    x, y = comb.decompress_host(kp.public_key)
    tab = comb.signer_table(kp.public_key)
    P = F.P_INT
    neg = ((P - x) % P, y)
    # [3 * 16^2](-A) by schoolbook host math
    pt = comb._EXT_IDENTITY
    base = (neg[0], neg[1], 1, neg[0] * neg[1] % P)
    for _ in range(2 * 4):  # 16^2 = 2 windows of 4 doublings
        base = comb._ext_add(base, base)
    for _ in range(3):
        pt = comb._ext_add(pt, base)
    (ax, ay), = comb._batch_affine([pt])
    row = tab[2, 3]
    np.testing.assert_array_equal(row[: F.NLIMBS], F.int_to_limbs((ay + ax) % P))
    np.testing.assert_array_equal(
        row[F.NLIMBS : 2 * F.NLIMBS], F.int_to_limbs((ay - ax) % P)
    )
    np.testing.assert_array_equal(
        row[2 * F.NLIMBS :], F.int_to_limbs(2 * F.D_INT * ax % P * ay % P)
    )


@pytest.mark.slow
def test_device_matmuls_pin_highest_precision():
    """Every dot_general in the comb programs must carry explicit
    Precision.HIGHEST: TPU's DEFAULT f32 matmul decomposes through bf16
    passes whose 8-bit mantissa truncates the 15-bit table limbs — wrong
    basepoint rows, valid signatures rejected (ADVICE r4 medium; the CPU
    backend computes full f32 either way, which is exactly why a numeric
    test here cannot catch it and this structural check exists)."""
    import jax

    from mochi_tpu.crypto.batch_verify import prepare_packed

    reg = comb.SignerRegistry()
    kps = [keys.keypair_from_seed(bytes([i + 1] * 32)) for i in range(2)]
    for kp in kps:
        assert reg.register(kp.public_key) is not None
    items = [
        VerifyItem(kp.public_key, b"p%d" % i, kp.sign(b"p%d" % i))
        for i, kp in enumerate(kps)
    ]
    _, _, y_r, sign_r, s_sc, h_sc, ok = prepare_packed(items)
    assert ok.all()
    key_idx = np.asarray(
        [reg.index_of(it.public_key) for it in items], dtype=np.int32
    )
    table = reg.device_table()

    def dot_precisions(jaxpr, out):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "dot_general":
                out.append(eqn.params.get("precision"))
            for v in eqn.params.values():
                for x in v if isinstance(v, (list, tuple)) else (v,):
                    if hasattr(x, "jaxpr"):
                        dot_precisions(x.jaxpr, out)
        return out

    from jax import lax

    for impl, expect_dots in (("tree", True), ("chain", False)):
        jx = jax.make_jaxpr(
            lambda *a: comb.verify_comb_prepared(*a, impl=impl)
        )(table, key_idx, y_r, sign_r, s_sc, h_sc)
        precs = dot_precisions(jx.jaxpr, [])
        assert bool(precs) == expect_dots, (impl, precs)
        for p in precs:
            assert p == (lax.Precision.HIGHEST, lax.Precision.HIGHEST), (impl, p)

    # Same hazard, same pin for the MXU column-reduction multiply
    # (MOCHI_SKEW_IMPL=mxu; field.py:_mul_mxu documents the bound proof).
    import jax.numpy as jnp

    from mochi_tpu.crypto import field as F

    a = jnp.ones((F.NLIMBS, 4), jnp.int32)
    jx = jax.make_jaxpr(F._mul_mxu)(a, a)
    precs = dot_precisions(jx.jaxpr, [])
    assert precs, "mxu multiply lost its dot_general"
    for p in precs:
        assert p == (lax.Precision.HIGHEST, lax.Precision.HIGHEST), p

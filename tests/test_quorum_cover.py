"""Property tests for the client's quorum-cover machinery at large rf.

`_trim_to_quorum_cover` decides how many Ed25519 verifications the WHOLE
cluster pays per transaction (every replica in a key's set checks every
grant in the certificate — rf x |cert| verifies), and round 5's real
n=64 f=21 cluster exercises it at quorum=43 for the first time.  These
properties pin the contract the integration tests rely on:

- validity: the trimmed subset still gives every key >= quorum distinct
  in-replica-set OK voters (safety: a thin cover would fail Write2);
- tightness: with single-key transactions and all-OK grants the cover is
  EXACTLY quorum (each extra grant costs rf verifies cluster-wide);
- never worse than the input: |trimmed| <= |chosen|.

Randomized over n in {4..64} with seeded rng — failures reproduce.
"""

from __future__ import annotations

import random

from mochi_tpu.client.client import MochiDBClient
from mochi_tpu.cluster.config import ClusterConfig
from mochi_tpu.crypto.keys import generate_keypair
from mochi_tpu.protocol.messages import (
    Action,
    Grant,
    MultiGrant,
    Operation,
    Status,
    Transaction,
)


def _config(n: int) -> ClusterConfig:
    return ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{20000 + i}" for i in range(n)}, rf=n
    )


def _client(cfg: ClusterConfig) -> MochiDBClient:
    # No network use: only the pure cover/trim methods are exercised.
    return MochiDBClient(config=cfg, keypair=generate_keypair())


def _multigrant(server_id: str, keys, ts: int = 7) -> MultiGrant:
    return MultiGrant(
        grants={
            k: Grant(
                object_id=k,
                timestamp=ts,
                configstamp=1,
                transaction_hash=b"h" * 64,
                status=Status.OK,
            )
            for k in keys
        },
        client_id="c",
        server_id=server_id,
    )


def _txn(keys) -> Transaction:
    return Transaction(
        tuple(Operation(Action.WRITE, k, b"v") for k in keys)
    )


def _cover_valid(client, txn, cert_grants) -> bool:
    cfg = client.config
    for op in txn.operations:
        rset = set(cfg.replica_set_for_key(op.key))
        voters = {
            mg.server_id
            for mg in cert_grants
            if mg.server_id in rset
            and (g := mg.grants.get(op.key)) is not None
            and g.status == Status.OK
        }
        if len(voters) < cfg.quorum:
            return False
    return True


def test_single_key_cover_is_exactly_quorum():
    for n in (4, 7, 16, 64):
        cfg = _config(n)
        client = _client(cfg)
        txn = _txn(["k"])
        rset = cfg.replica_set_for_key("k")
        chosen = [_multigrant(sid, ["k"]) for sid in rset]  # all rf respond
        trimmed = client._trim_to_quorum_cover(txn, chosen)
        assert len(trimmed) == cfg.quorum, (n, len(trimmed))
        assert _cover_valid(client, txn, trimmed)


def test_random_multikey_covers_stay_valid_and_never_grow():
    rng = random.Random(20260731)
    for trial in range(25):
        n = rng.choice([4, 8, 16, 32, 64])
        cfg = _config(n)
        client = _client(cfg)
        keys = [f"k{trial}-{i}" for i in range(rng.randint(1, 4))]
        txn = _txn(keys)
        # responders: a random superset of some quorum per key (the
        # _quorum_grant_subset stage guarantees coverage before trimming
        # runs, so build inputs that satisfy it)
        responders = set()
        for k in keys:
            rset = list(cfg.replica_set_for_key(k))
            rng.shuffle(rset)
            take = rng.randint(cfg.quorum, len(rset))
            responders.update(rset[:take])
        chosen = []
        for sid in sorted(responders):
            # each server grants the keys it replicates
            mine = [k for k in keys if sid in cfg.replica_set_for_key(k)]
            if mine:
                chosen.append(_multigrant(sid, mine))
        assert _cover_valid(client, txn, chosen), "test setup broken"
        trimmed = client._trim_to_quorum_cover(txn, chosen)
        assert len(trimmed) <= len(chosen)
        assert _cover_valid(client, txn, trimmed), (
            n, keys, len(chosen), len(trimmed)
        )


def test_quorum_grant_subset_drops_conflicting_timestamps():
    """A lagging/Byzantine minority at a different timestamp must be
    dropped while the majority's certificate still forms — the liveness
    fix over the reference's unanimity requirement
    (``MochiDBClient.java:195-219``)."""
    cfg = _config(16)
    client = _client(cfg)
    txn = _txn(["k"])
    rset = list(cfg.replica_set_for_key("k"))
    good = [_multigrant(sid, ["k"], ts=7) for sid in rset[: cfg.quorum]]
    laggards = [_multigrant(sid, ["k"], ts=3) for sid in rset[cfg.quorum :]]
    subset = client._quorum_grant_subset(txn, good + laggards)
    assert subset is not None
    ids = {mg.server_id for mg in subset}
    assert ids == {mg.server_id for mg in good}

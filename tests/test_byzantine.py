"""Byzantine-behavior tests — the adversarial coverage the reference lacks
(SURVEY.md §4 "Gaps": no equivocating server, no forged certificate tests).

These become possible exactly because signatures exist: forged MultiGrants,
tampered envelopes, and replayed certificates must be rejected by the
verifier seam, and honest quorums must still make progress with f Byzantine
grant sources in the mix.
"""

import asyncio
from dataclasses import replace

from mochi_tpu.client import TransactionBuilder
from mochi_tpu.crypto import generate_keypair
from mochi_tpu.protocol import (
    Envelope,
    FailType,
    HelloToServer,
    MultiGrant,
    RequestFailedFromServer,
    Write1OkFromServer,
    Write1ToServer,
    Write2AnsFromServer,
    Write2ToServer,
    WriteCertificate,
    transaction_hash,
)
from mochi_tpu.testing import VirtualCluster


def run(coro):
    asyncio.run(asyncio.wait_for(coro, timeout=60))


async def write1_via_wire(vc, client, txn, seed=77):
    """Collect signed MultiGrants from every replica over the wire."""
    blind = client._write1_transaction(txn)
    grants = {}
    for sid, info in sorted(vc.config.servers.items()):
        env = client._envelope(
            Write1ToServer(client.client_id, blind, seed, transaction_hash(txn)), f"w1-{sid}"
        )
        resp = await client.pool.send_and_receive(info, env)
        assert isinstance(resp.payload, Write1OkFromServer)
        grants[sid] = resp.payload.multi_grant
    return grants


def test_forged_multigrant_dropped_but_honest_quorum_commits():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            txn = TransactionBuilder().write("k", b"honest").build()
            grants = await write1_via_wire(vc, client, txn)

            # Attacker replaces one server's grant with a forgery "signed" by
            # a key the attacker controls.
            attacker = generate_keypair()
            victim = "server-1"
            forged = replace(grants[victim], signature=None)
            forged = forged.with_signature(attacker.sign(forged.signing_bytes()))
            wc = WriteCertificate({**grants, victim: forged})

            env = client._envelope(Write2ToServer(wc, txn), "w2-forged")
            resp = await client.pool.send_and_receive(
                vc.config.servers["server-0"], env
            )
            # 3 honest grants remain = quorum for rf=4 → commit succeeds
            assert isinstance(resp.payload, Write2AnsFromServer)
            assert resp.payload.result.operations[0].value == b"honest"
            # and the forged grant was detected and dropped
            assert vc.replicas[0].metrics.counters.get("replica.dropped-grants", 0) == 1

    run(main())


def test_certificate_below_quorum_after_forgeries_rejected():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            txn = TransactionBuilder().write("k", b"v").build()
            grants = await write1_via_wire(vc, client, txn)

            attacker = generate_keypair()
            wc_grants = dict(grants)
            for victim in ("server-1", "server-2"):  # forge 2 of 4 → only 2 honest < quorum 3
                forged = replace(wc_grants[victim], signature=None)
                wc_grants[victim] = forged.with_signature(
                    attacker.sign(forged.signing_bytes())
                )
            env = client._envelope(
                Write2ToServer(WriteCertificate(wc_grants), txn), "w2-thin"
            )
            resp = await client.pool.send_and_receive(vc.config.servers["server-0"], env)
            assert isinstance(resp.payload, RequestFailedFromServer)
            assert resp.payload.fail_type == FailType.BAD_CERTIFICATE

    run(main())


def test_tampered_multigrant_content_rejected():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            txn = TransactionBuilder().write("k", b"v").build()
            grants = await write1_via_wire(vc, client, txn)
            # Tamper with a signed grant's timestamp without re-signing: the
            # signature no longer covers the content.
            victim = "server-2"
            mg = grants[victim]
            bad = MultiGrant(
                grants={
                    k: replace(g, timestamp=g.timestamp + 5) for k, g in mg.grants.items()
                },
                client_id=mg.client_id,
                server_id=mg.server_id,
                signature=mg.signature,
            )
            wc = WriteCertificate({**grants, victim: bad})
            env = client._envelope(Write2ToServer(wc, txn), "w2-tamper")
            resp = await client.pool.send_and_receive(vc.config.servers["server-0"], env)
            # Tampered grant dropped; remaining 3 honest grants still commit.
            assert isinstance(resp.payload, Write2AnsFromServer)

    run(main())


def test_client_envelope_tampering_rejected_when_auth_required():
    async def main():
        async with VirtualCluster(4, rf=4, require_client_auth=True) as vc:
            client = vc.client()
            # Legitimate signed request works.
            ok = await client.execute_write_transaction(
                TransactionBuilder().write("k", b"v").build()
            )
            assert ok.operations[0].value == b"v"

            # Tampered envelope: signature is over different content.
            env = client._envelope(HelloToServer("legit"), "m-legit")
            tampered = replace(env, payload=HelloToServer("evil"))
            resp = await client.pool.send_and_receive(
                vc.config.servers["server-0"], tampered
            )
            assert isinstance(resp.payload, RequestFailedFromServer)
            assert resp.payload.fail_type == FailType.BAD_SIGNATURE

    run(main())


def test_unknown_client_rejected_when_auth_required():
    async def main():
        async with VirtualCluster(4, rf=4, require_client_auth=True) as vc:
            legit = vc.client()  # registers its key
            # A client whose key is NOT registered:
            rogue = legit.__class__(config=vc.config)
            try:
                env = rogue._envelope(HelloToServer("hi"), "m-rogue")
                resp = await rogue.pool.send_and_receive(
                    vc.config.servers["server-0"], env
                )
                assert isinstance(resp.payload, RequestFailedFromServer)
                assert resp.payload.fail_type == FailType.BAD_SIGNATURE
            finally:
                await rogue.close()

    run(main())


def test_response_impersonation_dropped_by_client():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            # A response claiming to be server-0 but signed by an attacker key
            # must not count toward quorums.
            attacker = generate_keypair()
            env = Envelope(HelloToServer("x"), "m1", "server-0", reply_to="m0")
            env = env.with_signature(attacker.sign(env.signing_bytes()))
            assert not client._authentic("server-0", env)

    run(main())


def test_durable_client_registry_enables_auth():
    """An unregistered client is rejected under require_client_auth; after
    an admin commits its key to _CONFIG_CLIENT_<id>, it can transact (the
    deployable path for the secure posture — VERDICT r1 weak #8)."""

    async def main():
        async with VirtualCluster(4, rf=4, require_client_auth=True) as vc:
            admin = vc.client()  # registered via the in-memory test registry
            from mochi_tpu.client.client import MochiDBClient

            outsider = MochiDBClient(config=vc.config)
            try:
                try:
                    await outsider.execute_write_transaction(
                        TransactionBuilder().write("ok", b"v").build()
                    )
                    raise AssertionError("unregistered client should fail")
                except AssertionError:
                    raise
                except Exception:
                    pass

                await admin.register_client_key(
                    outsider.client_id, outsider.keypair.public_key
                )
                await outsider.execute_write_transaction(
                    TransactionBuilder().write("ok", b"v").build()
                )
                res = await outsider.execute_read_transaction(
                    TransactionBuilder().read("ok").build()
                )
                assert res.operations[0].value == b"v"
            finally:
                await outsider.close()

    run(main())


def test_certificate_replay_against_different_transaction():
    """VERDICT r1 task 8(b): a committed certificate replayed with a
    DIFFERENT transaction must fail the per-grant transaction-hash check
    (the reference's check at ``InMemoryDataStore.java:580,591``)."""

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            txn1 = TransactionBuilder().write("rk", b"legit").build()
            grants = await write1_via_wire(vc, client, txn1)
            wc = WriteCertificate(grants)
            for sid, info in sorted(vc.config.servers.items()):
                env = client._envelope(Write2ToServer(wc, txn1), f"w2-legit-{sid}")
                resp = await client.pool.send_and_receive(info, env)
                assert isinstance(resp.payload, Write2AnsFromServer)

            # Replay the SAME (validly signed) certificate with another txn.
            txn2 = TransactionBuilder().write("rk", b"evil").build()
            env = client._envelope(Write2ToServer(wc, txn2), "w2-replay")
            resp = await client.pool.send_and_receive(vc.config.servers["server-0"], env)
            assert isinstance(resp.payload, RequestFailedFromServer)
            assert resp.payload.fail_type == FailType.BAD_CERTIFICATE
            # and the value is untouched
            r = await client.execute_read_transaction(
                TransactionBuilder().read("rk").build()
            )
            assert r.operations[0].value == b"legit"

    run(main())


def test_equivocating_server_cannot_flip_a_commit():
    """VERDICT r1 task 8(a): one in-set server (<= f) signs a CONFLICTING
    grant — same key, same timestamp, different transaction — for a second
    client.  The equivocation is validly signed, but a single equivocator
    can never assemble 2f+1 grants for the conflicting transaction, so the
    honest commit stands."""

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            txn1 = TransactionBuilder().write("eq", b"honest").build()
            grants = await write1_via_wire(vc, client, txn1, seed=123)
            ts = next(iter(grants["server-1"].grants.values())).timestamp

            # commit txn1 with the full honest certificate on every replica
            for sid, info in sorted(vc.config.servers.items()):
                env = client._envelope(
                    Write2ToServer(WriteCertificate(grants), txn1), f"w2-h-{sid}"
                )
                resp = await client.pool.send_and_receive(info, env)
                assert isinstance(resp.payload, Write2AnsFromServer)

            # server-1 equivocates: signs a grant for txn2 at the SAME ts
            # (we have its real key — VirtualCluster exposes keypairs)
            txn2 = TransactionBuilder().write("eq", b"evil").build()
            from mochi_tpu.protocol import Grant, Status

            evil_grant = Grant("eq", ts, vc.config.configstamp, transaction_hash(txn2), Status.OK)
            evil_mg = MultiGrant({"eq": evil_grant}, client.client_id, "server-1")
            evil_mg = evil_mg.with_signature(
                vc.keypairs["server-1"].sign(evil_mg.signing_bytes())
            )
            thin_wc = WriteCertificate({"server-1": evil_mg})
            env = client._envelope(Write2ToServer(thin_wc, txn2), "w2-eq")
            resp = await client.pool.send_and_receive(vc.config.servers["server-0"], env)
            # validly signed but 1 < quorum 3 → rejected
            assert isinstance(resp.payload, RequestFailedFromServer)
            assert resp.payload.fail_type == FailType.BAD_CERTIFICATE
            r = await client.execute_read_transaction(
                TransactionBuilder().read("eq").build()
            )
            assert r.operations[0].value == b"honest"

    run(main())


def test_restart_storm_with_resync_under_load():
    """VERDICT r1 task 8(c): f+1 simultaneous restarts while writers keep
    running; restarted replicas resync and the cluster converges with no
    inconsistency."""

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            committed = {}

            async def writer(tag: str, n: int):
                c = vc.client()
                for i in range(n):
                    key = f"storm-{tag}-{i}"
                    val = b"v-" + tag.encode() + b"-%d" % i
                    try:
                        await c.execute_write_transaction(
                            TransactionBuilder().write(key, val).build()
                        )
                        committed[key] = val
                    except Exception:
                        pass  # transient quorum loss during the storm is legal
                await c.close()

            async def storm():
                await asyncio.sleep(0.05)
                # f+1 = 2 simultaneous restarts, resync on boot
                await asyncio.gather(
                    vc.restart_replica("server-1", resync=True),
                    vc.restart_replica("server-2", resync=True),
                )

            await asyncio.gather(writer("a", 15), writer("b", 15), storm())
            assert committed, "no write survived the storm"

            # everything acknowledged must read back consistently
            for key, val in committed.items():
                r = await client.execute_read_transaction(
                    TransactionBuilder().read(key).build()
                )
                assert r.operations[0].value == val, key

            # restarted replicas hold resynced state for acknowledged keys
            fresh = vc.replica("server-1")
            owned = [k for k in committed if fresh.store.owns(k)]
            have = sum(
                1
                for k in owned
                if (sv := fresh.store._get(k)) is not None and sv.current_certificate
            )
            assert owned and have >= len(owned) // 2, (have, len(owned))

    run(main())

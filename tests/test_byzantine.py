"""Byzantine-behavior tests — the adversarial coverage the reference lacks
(SURVEY.md §4 "Gaps": no equivocating server, no forged certificate tests).

These become possible exactly because signatures exist: forged MultiGrants,
tampered envelopes, and replayed certificates must be rejected by the
verifier seam, and honest quorums must still make progress with f Byzantine
grant sources in the mix.
"""

import asyncio
from dataclasses import replace

from mochi_tpu.client import TransactionBuilder
from mochi_tpu.crypto import generate_keypair
from mochi_tpu.protocol import (
    Envelope,
    FailType,
    HelloToServer,
    MultiGrant,
    RequestFailedFromServer,
    Write1OkFromServer,
    Write1ToServer,
    Write2AnsFromServer,
    Write2ToServer,
    WriteCertificate,
    transaction_hash,
)
from mochi_tpu.testing import VirtualCluster


def run(coro):
    asyncio.run(asyncio.wait_for(coro, timeout=60))


async def write1_via_wire(vc, client, txn, seed=77):
    """Collect signed MultiGrants from every replica over the wire."""
    blind = client._write1_transaction(txn)
    grants = {}
    for sid, info in sorted(vc.config.servers.items()):
        env = client._envelope(
            Write1ToServer(client.client_id, blind, seed, transaction_hash(txn)), f"w1-{sid}"
        )
        resp = await client.pool.send_and_receive(info, env)
        assert isinstance(resp.payload, Write1OkFromServer)
        grants[sid] = resp.payload.multi_grant
    return grants


def test_forged_multigrant_dropped_but_honest_quorum_commits():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            txn = TransactionBuilder().write("k", b"honest").build()
            grants = await write1_via_wire(vc, client, txn)

            # Attacker replaces one server's grant with a forgery "signed" by
            # a key the attacker controls.
            attacker = generate_keypair()
            victim = "server-1"
            forged = replace(grants[victim], signature=None)
            forged = forged.with_signature(attacker.sign(forged.signing_bytes()))
            wc = WriteCertificate({**grants, victim: forged})

            env = client._envelope(Write2ToServer(wc, txn), "w2-forged")
            resp = await client.pool.send_and_receive(
                vc.config.servers["server-0"], env
            )
            # 3 honest grants remain = quorum for rf=4 → commit succeeds
            assert isinstance(resp.payload, Write2AnsFromServer)
            assert resp.payload.result.operations[0].value == b"honest"
            # and the forged grant was detected and dropped
            assert vc.replicas[0].metrics.counters.get("replica.dropped-grants", 0) == 1

    run(main())


def test_certificate_below_quorum_after_forgeries_rejected():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            txn = TransactionBuilder().write("k", b"v").build()
            grants = await write1_via_wire(vc, client, txn)

            attacker = generate_keypair()
            wc_grants = dict(grants)
            for victim in ("server-1", "server-2"):  # forge 2 of 4 → only 2 honest < quorum 3
                forged = replace(wc_grants[victim], signature=None)
                wc_grants[victim] = forged.with_signature(
                    attacker.sign(forged.signing_bytes())
                )
            env = client._envelope(
                Write2ToServer(WriteCertificate(wc_grants), txn), "w2-thin"
            )
            resp = await client.pool.send_and_receive(vc.config.servers["server-0"], env)
            assert isinstance(resp.payload, RequestFailedFromServer)
            assert resp.payload.fail_type == FailType.BAD_CERTIFICATE

    run(main())


def test_tampered_multigrant_content_rejected():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            txn = TransactionBuilder().write("k", b"v").build()
            grants = await write1_via_wire(vc, client, txn)
            # Tamper with a signed grant's timestamp without re-signing: the
            # signature no longer covers the content.
            victim = "server-2"
            mg = grants[victim]
            bad = MultiGrant(
                grants={
                    k: replace(g, timestamp=g.timestamp + 5) for k, g in mg.grants.items()
                },
                client_id=mg.client_id,
                server_id=mg.server_id,
                signature=mg.signature,
            )
            wc = WriteCertificate({**grants, victim: bad})
            env = client._envelope(Write2ToServer(wc, txn), "w2-tamper")
            resp = await client.pool.send_and_receive(vc.config.servers["server-0"], env)
            # Tampered grant dropped; remaining 3 honest grants still commit.
            assert isinstance(resp.payload, Write2AnsFromServer)

    run(main())


def test_client_envelope_tampering_rejected_when_auth_required():
    async def main():
        async with VirtualCluster(4, rf=4, require_client_auth=True) as vc:
            client = vc.client()
            # Legitimate signed request works.
            ok = await client.execute_write_transaction(
                TransactionBuilder().write("k", b"v").build()
            )
            assert ok.operations[0].value == b"v"

            # Tampered envelope: signature is over different content.
            env = client._envelope(HelloToServer("legit"), "m-legit")
            tampered = replace(env, payload=HelloToServer("evil"))
            resp = await client.pool.send_and_receive(
                vc.config.servers["server-0"], tampered
            )
            assert isinstance(resp.payload, RequestFailedFromServer)
            assert resp.payload.fail_type == FailType.BAD_SIGNATURE

    run(main())


def test_unknown_client_rejected_when_auth_required():
    async def main():
        async with VirtualCluster(4, rf=4, require_client_auth=True) as vc:
            legit = vc.client()  # registers its key
            # A client whose key is NOT registered:
            rogue = legit.__class__(config=vc.config)
            try:
                env = rogue._envelope(HelloToServer("hi"), "m-rogue")
                resp = await rogue.pool.send_and_receive(
                    vc.config.servers["server-0"], env
                )
                assert isinstance(resp.payload, RequestFailedFromServer)
                assert resp.payload.fail_type == FailType.BAD_SIGNATURE
            finally:
                await rogue.close()

    run(main())


def test_response_impersonation_dropped_by_client():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            # A response claiming to be server-0 but signed by an attacker key
            # must not count toward quorums.
            attacker = generate_keypair()
            env = Envelope(HelloToServer("x"), "m1", "server-0", reply_to="m0")
            env = env.with_signature(attacker.sign(env.signing_bytes()))
            assert not client._authentic("server-0", env)

    run(main())

"""CPU dry-run of the flash-capture path (scripts/tpu_flash.py).

The flash script is the battery's first action in a live TPU window; a
bug discovered on-chip would waste the window.  This runs the COMPLETE
code path — prepare, jit+compile, sequential + pipelined timing with
per-batch readback, CPU baseline, atomic merge — on the CPU backend with
a tiny batch, and checks the merge policy (a cpu capture must never
claim the round headline slot).
"""

import importlib.util
import json

import pytest
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_flash():
    spec = importlib.util.spec_from_file_location(
        "tpu_flash", os.path.join(REPO, "scripts", "tpu_flash.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_flash_capture_dryrun(tmp_path, monkeypatch):
    flash = _load_flash()
    monkeypatch.setattr(flash, "_REPO", str(tmp_path))
    os.makedirs(tmp_path / "benchmarks")
    monkeypatch.setattr(sys, "argv", ["tpu_flash.py", "97"])

    headline = flash.main(batch=32, require_tpu=False)
    assert headline["metric"] == "ed25519_batch_verify_throughput"
    assert headline["value"] > 0
    assert set(headline["pipelined_sigs_per_sec_by_depth"]) == {4, 8}

    out = json.load(open(tmp_path / "benchmarks" / "results_r97_tpu.json"))
    assert out["flash"]["value"] == headline["value"]
    # cpu platform must NOT claim the round's headline slot
    assert "headline" not in out

    # a tpu-platform sigs/sec record does claim it, and only better ones
    # replace it
    sig = {"metric": "ed25519_batch_verify_throughput", "platform": "tpu"}
    flash.merge_round_results("97", "x", dict(sig, value=10.0))
    flash.merge_round_results("97", "y", dict(sig, value=5.0))
    out = json.load(open(tmp_path / "benchmarks" / "results_r97_tpu.json"))
    assert out["headline"]["value"] == 10.0

    # other metrics must NOT claim the headline slot even with a huge
    # value: vpu_peak's ~1.8e12 int-ops/s would clobber the live capture
    # with a units-confused figure (review r5)
    flash.merge_round_results(
        "97", "vpu_peak",
        {"metric": "vpu_int32_madd_peak", "platform": "tpu", "value": 1.8e12},
    )
    out = json.load(open(tmp_path / "benchmarks" / "results_r97_tpu.json"))
    assert out["headline"]["value"] == 10.0


def test_flash_skips_when_already_banked(tmp_path, monkeypatch):
    """A retry battery must not spend a fresh live window re-measuring a
    completed flash — but a mid-run 'flash-seq' banking must NOT skip (the
    pipelined upgrade still needs to run)."""
    flash = _load_flash()

    # The discrimination itself (capture kind + platform), directly:
    assert flash.flash_already_banked({"platform": "tpu", "capture": "flash"})
    assert not flash.flash_already_banked({"platform": "tpu", "capture": "flash-seq"})
    assert not flash.flash_already_banked({"platform": "cpu", "capture": "flash"})
    assert not flash.flash_already_banked({})

    # And main()'s early return actually consults it (before any backend
    # work, so require_tpu=True is safe on the CPU-only test host):
    monkeypatch.setattr(flash, "_REPO", str(tmp_path))
    os.makedirs(tmp_path / "benchmarks")
    monkeypatch.setattr(sys, "argv", ["tpu_flash.py", "98"])
    path = tmp_path / "benchmarks" / "results_r98_tpu.json"
    done = {"platform": "tpu", "capture": "flash", "value": 111000.0}
    path.write_text(json.dumps({"flash": done}))
    assert flash.main(batch=32, require_tpu=True) == done


def test_ab_report_parses_battery_log():
    spec = importlib.util.spec_from_file_location(
        "ab_report", os.path.join(REPO, "scripts", "ab_report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    log = (
        "MAX_BUCKET=8192: 91125.3 sigs/s (89.9 ms)\n"
        "MAX_BUCKET=16384: 54952.9 sigs/s (298.1 ms)\n"
        "MOCHI_SELECT_IMPL=stacked: best 95000.0 sigs/s at batch 8192\n"
        "MOCHI_SELECT_IMPL=per-coord: best 91000.0 sigs/s at batch 8192\n"
        "MOCHI_SKEW_IMPL=mxu: best 101000.0 sigs/s at batch 8192\n"
        "unroll=2:    104000.0 sigs/s pipelined-4   (compile 30.5s)\n"
    )
    rec = mod.parse(log)
    assert rec["max_bucket_winner"] == "8192"
    assert rec["select_winner"] == "MOCHI_SELECT_IMPL=stacked"
    assert rec["mxu_vs_pad_skew"] == 1.11
    assert rec["unroll_winner"] == "2"
    assert mod.parse("") == {}

"""Admission control (overload shedding) mechanics.

The policy under test (``replica.py``): an event-loop lag monitor drives a
proportional shed probability; Write1s are shed by a DETERMINISTIC draw
keyed on (client_id, seed) so every replica sheds the same transactions
(independent coin flips would collapse the 2f+1 grant quorum); Write2 and
reads are never shed (admitted work drains); admin ops are never shed; the
client treats OVERLOADED as flow control (jittered backoff, no refusal
budget burned) and surfaces hard overload as a typed failure in bounded
time.  The reference has no admission control (``MochiServer.java:36-54``
just queues).
"""

from __future__ import annotations

import asyncio

import pytest

from mochi_tpu.client.errors import RequestRefused
from mochi_tpu.client.txn import TransactionBuilder
from mochi_tpu.protocol.messages import FailType, RequestFailedFromServer
from mochi_tpu.testing.virtual_cluster import VirtualCluster


def test_forced_shed_bounces_writes_and_client_fails_fast():
    """With every replica's shed probability pinned to 1.0, writes must be
    shed cluster-wide and the client must fail with a typed RequestRefused
    quickly (3 all-shed rounds), not burn its whole retry budget."""

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client(timeout_s=5.0)
            # establish sessions + working baseline
            await client.execute_write_transaction(
                TransactionBuilder().write("k", b"v").build()
            )
            for r in vc.replicas:
                r._shed_p = 1.0
                if r._lag_task is not None:  # freeze the controller
                    r._lag_task.cancel()
            t0 = asyncio.get_event_loop().time()
            with pytest.raises(RequestRefused, match="overloaded"):
                await client.execute_write_transaction(
                    TransactionBuilder().write("k2", b"v").build()
                )
            elapsed = asyncio.get_event_loop().time() - t0
            assert elapsed < 4.0, f"give-up took {elapsed:.1f}s — not bounded"
            sheds = sum(
                r.metrics.counters.get("replica.write1-shed", 0) for r in vc.replicas
            )
            assert sheds >= 5 * 4  # >= 5 rounds x replica-set fan-out
            # reads are never shed: admitted work still completes
            res = await client.execute_read_transaction(
                TransactionBuilder().read("k").build()
            )
            assert res.operations[0].value == b"v"

    asyncio.run(main())


def test_partial_shed_retries_through():
    """At a moderate shed probability the client's keyed-draw retries (fresh
    seed = fresh draw) must get the write through without an error."""

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client(timeout_s=5.0)
            await client.execute_write_transaction(
                TransactionBuilder().write("k", b"v").build()
            )
            for r in vc.replicas:
                r._shed_p = 0.3
                if r._lag_task is not None:
                    r._lag_task.cancel()
            for i in range(6):
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"p{i}", b"x").build()
                )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("p5").build()
            )
            assert res.operations[0].value == b"x"

    asyncio.run(main())


def test_shed_draw_is_identical_across_replicas():
    """The admission draw is a pure function of (client_id, seed): replicas
    agree exactly, which is what keeps quorums alive under shedding."""
    from mochi_tpu.server.replica import MochiReplica

    class P:
        client_id = "client-abc"
        seed = 123456

    d = MochiReplica._shed_draw(P())
    assert 0.0 <= d < 1.0
    assert d == MochiReplica._shed_draw(P())
    P.seed = 123457
    assert d != MochiReplica._shed_draw(P())


def test_admin_ops_never_shed():
    """An operator reconfiguring an overloaded cluster must get through:
    admin-gated writes bypass admission control."""

    async def main():
        from mochi_tpu.crypto.keys import generate_keypair

        admin_kp = generate_keypair()
        async with VirtualCluster(5, rf=4) as vc:
            for r in vc.replicas:
                r.config.admin_keys.append(admin_kp.public_key)
                r._shed_p = 1.0
                if r._lag_task is not None:
                    r._lag_task.cancel()
            client = vc.client(keypair=admin_kp)
            # _CONFIG_ keyspace write = admin op; must commit despite p=1.0
            from mochi_tpu.cluster.config import CONFIG_CLIENT_PREFIX

            await client.execute_write_transaction(
                TransactionBuilder()
                .write(CONFIG_CLIENT_PREFIX + "ops-client", b"\x01" * 32)
                .build()
            )

    asyncio.run(main())

"""Admission control (overload shedding) mechanics.

The policy under test (``server/replica.py`` + ``server/admission.py``): a
DETERMINISTIC load signal (dispatch pressure, verify occupancy, send-queue
pressure — all event-counted, never wall-clock) drives a proportional shed
probability; Write1s are shed by a deterministic draw keyed on (client_id,
seed) so every replica sheds the same transactions (independent coin flips
would collapse the 2f+1 grant quorum); shed responses carry a typed
``OVERLOADED`` + retry-after hint the client's backoff honors; Write2 and
reads are never shed (admitted work drains); admin ops are never shed; the
client surfaces hard overload as a typed failure in bounded time.  The
reference has no admission control (``MochiServer.java:36-54`` just
queues).  Unlike the retired wall-clock loop-lag signal (OFF since PR 1
because harness stalls tripped it), admission defaults ON everywhere —
``test_default_admission_never_sheds_light_load`` pins the no-flake claim.
"""

from __future__ import annotations

import asyncio

import pytest

from mochi_tpu.client.client import MAX_ALL_SHED_ROUNDS
from mochi_tpu.client.errors import RequestRefused
from mochi_tpu.client.txn import TransactionBuilder
from mochi_tpu.protocol.messages import FailType, RequestFailedFromServer
from mochi_tpu.testing.virtual_cluster import VirtualCluster


def _pin_shed(vc, p: float, retry_after_ms: int = 0) -> None:
    """Freeze every replica's controller at shed probability ``p`` (the
    property setter pins it) and, when given, at a fixed retry-after hint
    (update() is stubbed out so the hint survives the next Write1 batch)."""
    for r in vc.replicas:
        r._shed_p = p
        if retry_after_ms:
            r._admission.retry_after_ms = retry_after_ms
            r._admission.update = lambda: None


def test_forced_shed_bounces_writes_and_client_fails_fast():
    """With every replica's shed probability pinned to 1.0, writes must be
    shed cluster-wide and the client must fail with a typed RequestRefused
    quickly (MAX_ALL_SHED_ROUNDS all-shed rounds), not burn its whole
    retry budget."""

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client(timeout_s=5.0)
            # establish sessions + working baseline
            await client.execute_write_transaction(
                TransactionBuilder().write("k", b"v").build()
            )
            _pin_shed(vc, 1.0)
            t0 = asyncio.get_event_loop().time()
            with pytest.raises(RequestRefused, match="overloaded"):
                await client.execute_write_transaction(
                    TransactionBuilder().write("k2", b"v").build()
                )
            elapsed = asyncio.get_event_loop().time() - t0
            assert elapsed < 4.0, f"give-up took {elapsed:.1f}s — not bounded"
            sheds = sum(
                r.metrics.counters.get("replica.write1-shed", 0) for r in vc.replicas
            )
            assert sheds >= 5 * 4  # >= 5 rounds x replica-set fan-out
            # reads are never shed: admitted work still completes
            res = await client.execute_read_transaction(
                TransactionBuilder().read("k").build()
            )
            assert res.operations[0].value == b"v"

    asyncio.run(main())


def test_full_overload_arc_shed_backoff_retry_after_refused():
    """The whole client arc under hard overload, end to end: Write1s shed
    with typed OVERLOADED carrying a retry-after hint -> the client's
    jittered backoff honors the hint (the inter-round wait is at least
    0.75x the hint, so total elapsed has a hard floor) -> after
    MAX_ALL_SHED_ROUNDS consecutive fully-shed rounds the client surfaces
    a typed RequestRefused."""

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client(timeout_s=5.0)
            await client.execute_write_transaction(
                TransactionBuilder().write("warm", b"v").build()
            )
            hint_ms = 150
            _pin_shed(vc, 1.0, retry_after_ms=hint_ms)
            t0 = asyncio.get_event_loop().time()
            with pytest.raises(RequestRefused, match="overloaded"):
                await client.execute_write_transaction(
                    TransactionBuilder().write("k", b"v").build()
                )
            elapsed = asyncio.get_event_loop().time() - t0
            # the raise lands on round MAX_ALL_SHED_ROUNDS, after
            # (MAX_ALL_SHED_ROUNDS - 1) backoffs of >= 0.75 * hint each
            floor_s = (MAX_ALL_SHED_ROUNDS - 1) * hint_ms / 1e3 * 0.75
            assert elapsed >= floor_s, (
                f"client retried after {elapsed:.3f}s; retry-after hint of "
                f"{hint_ms}ms demands >= {floor_s:.3f}s — hint not honored"
            )
            assert elapsed < 6.0, f"give-up took {elapsed:.1f}s — not bounded"
            # the shed rounds were counted on the client (flow control, not
            # refusal budget)
            assert client.metrics.counters.get("client.write1-shed", 0) >= (
                MAX_ALL_SHED_ROUNDS
            )
            # shed responses really carried the hint on the wire
            shed_hints = [
                r._admission.retry_after_ms for r in vc.replicas
            ]
            assert all(h == hint_ms for h in shed_hints)

    asyncio.run(main())


def test_default_admission_never_sheds_light_load():
    """Admission control now defaults ON (the deterministic signal).  A
    light in-process workload — the exact posture that flaked the old
    wall-clock lag signal into shedding — must never shed: queued work
    stays far under every high-water mark."""

    async def main():
        async with VirtualCluster(5, rf=4) as vc:  # no admission override
            assert all(r._admission.enabled for r in vc.replicas)
            client = vc.client(timeout_s=5.0)
            for i in range(8):
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"k{i}", b"v").build()
                )
            assert all(
                r.metrics.counters.get("replica.write1-shed", 0) == 0
                for r in vc.replicas
            )
            assert all(r._admission.shed_p == 0.0 for r in vc.replicas)

    asyncio.run(main())


def test_partial_shed_retries_through():
    """At a moderate shed probability the client's keyed-draw retries (fresh
    seed = fresh draw) must get the write through without an error."""

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client(timeout_s=5.0)
            await client.execute_write_transaction(
                TransactionBuilder().write("k", b"v").build()
            )
            _pin_shed(vc, 0.3)
            for i in range(6):
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"p{i}", b"x").build()
                )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("p5").build()
            )
            assert res.operations[0].value == b"x"

    asyncio.run(main())


def test_shed_draw_is_identical_across_replicas():
    """The admission draw is a pure function of (client_id, seed): replicas
    agree exactly, which is what keeps quorums alive under shedding."""
    from mochi_tpu.server.replica import MochiReplica

    class P:
        client_id = "client-abc"
        seed = 123456

    d = MochiReplica._shed_draw(P())
    assert 0.0 <= d < 1.0
    assert d == MochiReplica._shed_draw(P())
    P.seed = 123457
    assert d != MochiReplica._shed_draw(P())


def test_admin_ops_never_shed():
    """An operator reconfiguring an overloaded cluster must get through:
    admin-gated writes bypass admission control."""

    async def main():
        from mochi_tpu.crypto.keys import generate_keypair

        admin_kp = generate_keypair()
        async with VirtualCluster(5, rf=4) as vc:
            for r in vc.replicas:
                r.config.admin_keys.append(admin_kp.public_key)
            _pin_shed(vc, 1.0)
            client = vc.client(keypair=admin_kp)
            # _CONFIG_ keyspace write = admin op; must commit despite p=1.0
            from mochi_tpu.cluster.config import CONFIG_CLIENT_PREFIX

            await client.execute_write_transaction(
                TransactionBuilder()
                .write(CONFIG_CLIENT_PREFIX + "ops-client", b"\x01" * 32)
                .build()
            )

    asyncio.run(main())


def test_overloaded_responses_carry_retry_after_on_real_signal():
    """Un-pinned controller: when the real load signal crosses its
    high-water mark, shed responses carry a non-zero retry-after hint
    (the hint is computed from the measured load factor, not a constant)."""

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client(timeout_s=5.0)
            await client.execute_write_transaction(
                TransactionBuilder().write("k", b"v").build()
            )
            r0 = vc.replicas[0]
            # drive the signal, not the knob: report verify backlog past
            # the high-water mark, as a flood of in-flight Write2s would
            r0._admission.verify_inflight = int(
                r0._admission.verify_hw * 3
            )
            r0._admission.update()
            assert r0._admission.overloaded
            assert r0._admission.retry_after_ms > 0
            assert r0._admission.shed_p > 0.0
            # and the typed response path forwards it
            from mochi_tpu.protocol.messages import Write1ToServer
            from mochi_tpu.protocol import transaction_hash

            txn = TransactionBuilder().write("shedme", b"x").build()
            blind = client._write1_transaction(txn)
            # pin the draw under shed_p by flooding attempts: with p ~> 0.5
            # a handful of seeds guarantees at least one shed
            r0._shed_p = 1.0
            env = client._envelope(
                Write1ToServer(client.client_id, blind, 7, transaction_hash(txn)),
                "probe-w1",
                r0.server_id,
            )
            resp = await r0.handle_envelope(env)
            payload = resp.payload
            assert isinstance(payload, RequestFailedFromServer)
            assert payload.fail_type == FailType.OVERLOADED
            assert payload.retry_after_ms > 0

    asyncio.run(main())

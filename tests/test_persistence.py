"""Snapshot persistence: round-trip, atomicity, and restart recovery.

The reference has zero durability (in-memory maps only, SURVEY.md §5); these
tests cover the new snapshot+reload path and its interplay with resync.
"""

import asyncio
import os

import pytest

from mochi_tpu.client import TransactionBuilder
from mochi_tpu.cluster.config import ClusterConfig
from mochi_tpu.server import persistence
from mochi_tpu.server.replica import MochiReplica
from mochi_tpu.server.store import DataStore
from mochi_tpu.testing import VirtualCluster


def run(coro):
    asyncio.run(asyncio.wait_for(coro, timeout=120))


def test_snapshot_roundtrip(tmp_path):
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("pk1", b"v1").write("pk2", b"v2").build()
            )
            await client.execute_write_transaction(
                TransactionBuilder().delete("pk2").build()
            )
            replica = vc.replicas[0]
            path = str(tmp_path / "snap")
            n_bytes = persistence.write_snapshot(replica.store, path)
            assert n_bytes > 0 and os.path.exists(path)

            fresh = DataStore(replica.server_id, vc.config)
            n = persistence.load_snapshot(fresh, path)
            assert n is not None and n >= 2
            assert fresh.data["pk1"].value == b"v1"
            assert fresh.data["pk1"].exists
            assert not fresh.data["pk2"].exists
            # certificates and epochs survive (what resync/write1 need)
            assert fresh.data["pk1"].current_certificate is not None
            assert fresh.data["pk1"].current_epoch == replica.store.data["pk1"].current_epoch
            assert fresh.data["pk1"].last_transaction is not None

    run(main())


def test_snapshot_reload_enables_writes_without_resync(tmp_path):
    """After restart-with-snapshot, epochs match the quorum again, so warm-key
    writes converge with no state transfer at all."""

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("durable", b"v1").build()
            )
            victim = vc.replica("server-0")
            path = str(tmp_path / "s0.snapshot")
            persistence.write_snapshot(victim.store, path)

            fresh = await vc.restart_replica("server-0")
            assert persistence.load_snapshot(fresh.store, path) >= 1

            await client.execute_write_transaction(
                TransactionBuilder().write("durable", b"v2").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("durable").build()
            )
            assert res.operations[0].value == b"v2"

    run(main())


def test_corrupt_snapshot_rejected(tmp_path):
    store = DataStore("server-x", _tiny_config())
    path = str(tmp_path / "bad")
    with open(path, "wb") as fh:
        fh.write(b"\x08\x01\x06\x05magic\x06\x03bad")
    with pytest.raises(ValueError):
        persistence.load_snapshot(store, path)
    assert persistence.load_snapshot(store, str(tmp_path / "missing")) is None


def _tiny_config():
    return ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{9000+i}" for i in range(4)}, rf=4
    )


def test_boot_installs_newer_config_from_snapshot(tmp_path):
    """A snapshot taken AFTER a reconfiguration holds the cs=2 membership;
    a replica booting from it with the old cs=1 config file must install
    the snapshot's config before serving (replica.start path)."""

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("bk", b"v").build()
            )
            old_config = vc.config
            urls = {sid: info.url for sid, info in vc.config.servers.items()}
            await client.reconfigure_cluster(vc.config.evolve(urls))

            donor = vc.replicas[0]
            assert donor.config.configstamp == 2
            path = str(tmp_path / "snap")
            persistence.write_snapshot(donor.store, path)

            # boot a replica from the snapshot but with the STALE config
            stale = ClusterConfig.from_json(old_config.to_json())
            stale.configstamp = 1
            fresh = MochiReplica(
                server_id=donor.server_id,
                config=stale,
                keypair=vc.keypairs[donor.server_id],
                host="127.0.0.1",
                port=0,
                snapshot_path=path,
            )
            await fresh.start()
            try:
                assert fresh.config.configstamp == 2, fresh.config.configstamp
                assert fresh.store.config.configstamp == 2
                sv = fresh.store._get("bk")
                assert sv is not None and sv.exists
            finally:
                await fresh.close()

    run(main())

"""End-to-end tests: full cluster over loopback TCP, production client SDK.

Ports the reference's integration progression
(``MochiClientServerCommunicationTest.java``): hello plumbing, write→read
round trips (``:173-255``), delete lifecycle (``:257-348``), sequential
overwrites (``:350-416``), concurrent clients on shared keys (``:418-634``),
and the multi-client disjoint-key stress sweep (``:636-758``) — all in signed
mode (every envelope and MultiGrant Ed25519-signed and verified), which the
reference never had.
"""

import asyncio
import random

import pytest

from mochi_tpu.client import InconsistentRead, TransactionBuilder
from mochi_tpu.protocol import HelloToServer, HelloFromServer, Envelope
from mochi_tpu.testing import VirtualCluster


def run(coro):
    asyncio.run(asyncio.wait_for(coro, timeout=60))


def test_hello_roundtrip():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            info = vc.config.servers["server-0"]
            env = client._envelope(HelloToServer("ping"), "m-1")
            resp = await client.pool.send_and_receive(info, env)
            assert isinstance(resp.payload, HelloFromServer)
            assert resp.payload.message == "ping back"

    run(main())


def test_write_then_read():
    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            txn = TransactionBuilder().write("greeting", b"hello world").build()
            result = await client.execute_write_transaction(txn)
            assert result.operations[0].value == b"hello world"

            read = await client.execute_read_transaction(
                TransactionBuilder().read("greeting").build()
            )
            assert read.operations[0].value == b"hello world"
            assert read.operations[0].existed
            # The read returns the write certificate established at commit
            # (ref: testReadOperation certificate assertions, :173-220).
            assert read.operations[0].current_certificate is not None
            assert len(read.operations[0].current_certificate.grants) >= vc.config.quorum

    run(main())


def test_read_missing_key():
    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            read = await client.execute_read_transaction(
                TransactionBuilder().read("never-written").build()
            )
            assert read.operations[0].value is None
            assert not read.operations[0].existed

    run(main())


def test_delete_lifecycle():
    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("doomed", b"x").build()
            )
            read = await client.execute_read_transaction(
                TransactionBuilder().read("doomed").build()
            )
            assert read.operations[0].existed
            await client.execute_write_transaction(
                TransactionBuilder().delete("doomed").build()
            )
            read = await client.execute_read_transaction(
                TransactionBuilder().read("doomed").build()
            )
            assert not read.operations[0].existed and read.operations[0].value is None

    run(main())


def test_sequential_overwrites():
    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            for value in (b"v1", b"v2", b"v3"):
                await client.execute_write_transaction(
                    TransactionBuilder().write("counter", value).build()
                )
            read = await client.execute_read_transaction(
                TransactionBuilder().read("counter").build()
            )
            assert read.operations[0].value == b"v3"

    run(main())


def test_multikey_transaction():
    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            txn = (
                TransactionBuilder().write("mk-a", b"1").write("mk-b", b"2").build()
            )
            result = await client.execute_write_transaction(txn)
            assert [o.value for o in result.operations] == [b"1", b"2"]
            read = await client.execute_read_transaction(
                TransactionBuilder().read("mk-a").read("mk-b").build()
            )
            assert [o.value for o in read.operations] == [b"1", b"2"]

    run(main())


def test_concurrent_clients_shared_keys():
    # ref: testWriteOperationConcurrent (:418-634) — interleavings are legal;
    # the invariant is that the final value is one of the written ones and all
    # replicas agree at read quorum.
    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            clients = [vc.client() for _ in range(5)]

            async def worker(client, idx):
                for round_no in range(3):
                    await client.execute_write_transaction(
                        TransactionBuilder()
                        .write("shared", f"client{idx}round{round_no}".encode())
                        .build()
                    )

            await asyncio.gather(*(worker(c, i) for i, c in enumerate(clients)))
            read = await clients[0].execute_read_transaction(
                TransactionBuilder().read("shared").build()
            )
            assert read.operations[0].value is not None
            value = read.operations[0].value.decode()
            assert value.startswith("client") and "round" in value

    run(main())


def test_stress_disjoint_keys():
    # ref: testWriteOperationConcurrentStressTest (:636-758) — N clients ×
    # disjoint keys, shuffled write → read-verify → delete sweep.  Scaled-down
    # key count to keep CI fast; the bench harness runs the full shape.
    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            clients = [vc.client() for _ in range(3)]

            async def worker(client, idx):
                keys = [f"stress-{idx}-{k}" for k in range(8)]
                random.Random(idx).shuffle(keys)
                for key in keys:
                    await client.execute_write_transaction(
                        TransactionBuilder().write(key, f"val-{key}".encode()).build()
                    )
                for key in keys:
                    read = await client.execute_read_transaction(
                        TransactionBuilder().read(key).build()
                    )
                    assert read.operations[0].value == f"val-{key}".encode()
                for key in keys:
                    await client.execute_write_transaction(
                        TransactionBuilder().delete(key).build()
                    )
                for key in keys:
                    read = await client.execute_read_transaction(
                        TransactionBuilder().read(key).build()
                    )
                    assert not read.operations[0].existed

            await asyncio.gather(*(worker(c, i) for i, c in enumerate(clients)))

    run(main())


def test_metrics_recorded():
    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("m", b"1").build()
            )
            await client.execute_read_transaction(TransactionBuilder().read("m").build())
            snap = client.metrics.snapshot()
            assert snap["timers"]["write-transactions"]["count"] == 1
            assert snap["timers"]["read-transactions"]["count"] == 1
            server_snap = vc.replicas[0].metrics.snapshot()
            assert server_snap["timers"]["replica.write1"]["count"] >= 1

    run(main())


def test_quorum_targets_cover_every_key():
    """The trimmed read fan-out must give every key >= quorum members of its
    own replica set, never exceed the union, and rotate across calls."""
    from mochi_tpu.client import MochiDBClient
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.cluster.config import ClusterConfig

    cfg = ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{9300 + i}" for i in range(7)}, rf=4
    )
    client = MochiDBClient(cfg)
    tb = TransactionBuilder()
    for i in range(6):
        tb.read(f"qt-key-{i}")
    txn = tb.build()
    full = dict(client._targets(txn))
    picks = set()
    for _ in range(8):
        chosen = dict(client._quorum_targets(txn))
        assert set(chosen) <= set(full)
        for op in txn.operations:
            rset = {s.server_id for s in cfg.servers_for_key(op.key)}
            assert len(rset & set(chosen)) >= cfg.quorum, op.key
        picks.add(tuple(sorted(chosen)))
    # single-key: exactly quorum-many targets, and the rotor varies them
    single = TransactionBuilder().read("qt-single").build()
    sizes = set()
    singles = set()
    for _ in range(8):
        chosen = client._quorum_targets(single)
        sizes.add(len(chosen))
        singles.add(tuple(sorted(sid for sid, _ in chosen)))
    assert sizes == {cfg.quorum}
    assert len(singles) > 1, "rotor never varied the chosen quorum"


def test_large_values_round_trip():
    """Values up to the MB range ride the normal 2-phase path (frames cap
    at 64 MiB); the acknowledged bytes must come back identical."""
    import os as _os

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            for size in (64 * 1024, 1024 * 1024):
                blob = _os.urandom(size)
                key = f"big-{size}"
                await client.execute_write_transaction(
                    TransactionBuilder().write(key, blob).build()
                )
                res = await client.execute_read_transaction(
                    TransactionBuilder().read(key).build()
                )
                assert res.operations[0].value == blob, size
            await client.close()

    run(main())


def test_trimmed_read_falls_back_when_chosen_replica_is_stale():
    """Force the quorum-sized read fan-out to include a replica that
    silently lost the key: the trimmed tally (2 of 3) must fail closed and
    the full-union fallback must still return the committed value."""

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("tr-key", b"val").build()
            )
            # wipe the key from one in-set replica (simulated silent loss)
            stale = vc.replicas[0]
            stale.store.data.pop("tr-key", None)

            # steer the rotor so the trimmed subset includes the stale
            # replica (rotor increments before use inside _quorum_targets)
            from mochi_tpu.client.txn import TransactionBuilder as TB

            txn = TB().read("tr-key").build()
            for rotor in range(4):
                client._read_rotor = rotor - 1
                chosen = {sid for sid, _ in client._quorum_targets(txn)}
                if stale.server_id in chosen:
                    client._read_rotor = rotor - 1
                    break
            else:
                raise AssertionError("rotor never selected the stale replica")

            before = client.metrics.timers["read-transactions"].count
            res = await client.execute_read_transaction(txn)
            assert res.operations[0].value == b"val"
            # the trimmed attempt and the full-union fallback each count
            assert client.metrics.timers["read-transactions"].count - before == 2
            await client.close()

    run(main())

"""Regression tests for review findings: out-of-set grants, signature
stripping, seed-range attacks, codec int domain, config token bounds."""

import asyncio

import pytest

from mochi_tpu.client import TransactionBuilder
from mochi_tpu.cluster import ClusterConfig
from mochi_tpu.protocol import (
    Envelope,
    FailType,
    HelloToServer,
    RequestFailedFromServer,
    Transaction,
    Operation,
    Action,
    Write1OkFromServer,
    Write1ToServer,
    Write2ToServer,
    WriteCertificate,
    transaction_hash,
)
from mochi_tpu.protocol.codec import decode, encode
from mochi_tpu.server.store import BadRequest, DataStore
from mochi_tpu.testing import VirtualCluster


def run(coro):
    asyncio.run(asyncio.wait_for(coro, timeout=60))


def test_out_of_set_grants_do_not_count_toward_quorum():
    # n=7, rf=4: servers outside a key's replica set may be compromised beyond
    # the in-set f assumption; their (validly signed) grants must not form a
    # committing certificate.
    cfg = ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{8001 + i}" for i in range(7)}, rf=4
    )
    stores = {f"server-{i}": DataStore(f"server-{i}", cfg) for i in range(7)}
    key = next(
        k for k in (f"key-{i}" for i in range(1000))
        if len(set(cfg.replica_set_for_key(k))) == 4
        and len(set(cfg.servers) - set(cfg.replica_set_for_key(k))) >= 3
    )
    in_set = cfg.replica_set_for_key(key)
    out_set = sorted(set(cfg.servers) - set(in_set))[:3]
    txn = Transaction((Operation(Action.WRITE, key, b"evil"),))
    blind = Transaction((Operation(Action.WRITE, key, None),))
    req = Write1ToServer("attacker", blind, 5, transaction_hash(txn))
    # Collect grants ONLY from out-of-set servers (they will issue them since
    # owns() is False → WRONG_SHARD... so craft via one in-set grant plus
    # out-of-set forgeries at the same timestamp).
    from mochi_tpu.protocol import Grant, MultiGrant, Status

    grants = {}
    for sid in out_set:
        grants[sid] = MultiGrant(
            grants={key: Grant(key, 5, 1, transaction_hash(txn), Status.OK)},
            client_id="attacker",
            server_id=sid,
        )
    wc = WriteCertificate(grants)
    victim = stores[in_set[0]]
    result = victim.process_write2(Write2ToServer(wc, txn))
    assert isinstance(result, RequestFailedFromServer)
    assert result.fail_type == FailType.BAD_CERTIFICATE
    assert victim.data.get(key) is None or victim.data[key].value != b"evil"


def test_signature_stripping_rejected_even_in_open_mode():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:  # open mode (no client auth)
            client = vc.client()
            env = Envelope(HelloToServer("spoof"), "m1", "server-1")  # known id, no sig
            resp = await client.pool.send_and_receive(vc.config.servers["server-0"], env)
            assert isinstance(resp.payload, RequestFailedFromServer)
            assert resp.payload.fail_type == FailType.BAD_SIGNATURE

    run(main())


def test_out_of_range_seed_rejected():
    cfg = ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{8001 + i}" for i in range(4)}, rf=4
    )
    store = DataStore("server-0", cfg)
    blind = Transaction((Operation(Action.WRITE, "k", None),))
    for bad_seed in (-1, 10**15, 1000):
        with pytest.raises(BadRequest):
            store.process_write1(Write1ToServer("c", blind, bad_seed, b"h"))


def test_out_of_range_seed_rejected_over_wire():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            blind = Transaction((Operation(Action.WRITE, "k", None),))
            env = client._envelope(
                Write1ToServer(client.client_id, blind, 10**12, b"h" * 64), "m-seed"
            )
            resp = await client.pool.send_and_receive(vc.config.servers["server-0"], env)
            assert isinstance(resp.payload, RequestFailedFromServer)
            assert resp.payload.fail_type == FailType.BAD_REQUEST

    run(main())


def test_codec_int_domain_symmetric():
    assert decode(encode((1 << 64) - 1)) == (1 << 64) - 1
    assert decode(encode(-(1 << 64))) == -(1 << 64)
    with pytest.raises(TypeError):
        encode(1 << 64)
    with pytest.raises(TypeError):
        encode(-(1 << 64) - 1)


def test_properties_token_bounds_checked():
    cfg = ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{8001 + i}" for i in range(4)}, rf=4
    )
    text = cfg.to_properties().replace("_TOKENS=0,", "_TOKENS=-1,", 1)
    with pytest.raises(ValueError, match="outside"):
        ClusterConfig.from_properties(text)


def test_timer_memory_bounded():
    from mochi_tpu.utils.metrics import Timer

    t = Timer(window=16)
    for i in range(1000):
        t.record(0.001)
    assert len(t.samples) == 16
    assert t.count == 1000
    assert t.snapshot()["count"] == 1000


def test_duplicate_key_transaction_cannot_inflate_quorum():
    # rf=4 (quorum 3): a txn repeating the same key must not let 2 servers'
    # grants count as 4 — one vote per (key, server) in Write2 coalescing.
    from mochi_tpu.protocol import Grant, MultiGrant, Status

    cfg = ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{8001 + i}" for i in range(4)}, rf=4
    )
    key = "dup-key"
    in_set = cfg.replica_set_for_key(key)
    txn = Transaction(
        (Operation(Action.WRITE, key, b"evil"), Operation(Action.WRITE, key, b"evil"))
    )
    h = transaction_hash(txn)
    grants = {
        sid: MultiGrant(
            grants={key: Grant(key, 5, 1, h, Status.OK)},
            client_id="attacker",
            server_id=sid,
        )
        for sid in in_set[:2]  # only 2 distinct servers < quorum 3
    }
    victim = DataStore(in_set[0], cfg)
    result = victim.process_write2(Write2ToServer(WriteCertificate(grants), txn))
    assert isinstance(result, RequestFailedFromServer)
    assert result.fail_type == FailType.BAD_CERTIFICATE
    assert victim.data.get(key) is None or victim.data[key].value != b"evil"


def test_read_tally_ignores_out_of_set_servers():
    # 10 servers, rf=4: 4 colluding servers OUTSIDE the key's replica set
    # answer OK with a forged value while only 3 in-set servers respond
    # honestly.  The client must take the honest 3 (== quorum), not the
    # forged 4.
    from mochi_tpu.client.client import MochiDBClient
    from mochi_tpu.protocol import OperationResult, ReadFromServer, Status, TransactionResult

    cfg = ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{8001 + i}" for i in range(10)}, rf=4
    )
    key = "oos-read-key"
    in_set = cfg.replica_set_for_key(key)
    out_set = sorted(set(cfg.servers) - set(in_set))[:4]
    client = MochiDBClient(cfg)
    txn = TransactionBuilder().read(key).build()

    async def fake_fan_out(transaction, make_payload, targets=None, **kw):
        payload = make_payload()
        nonce = payload.nonce
        honest = TransactionResult((OperationResult(b"good", None, True, Status.OK),))
        forged = TransactionResult((OperationResult(b"evil", None, True, Status.OK),))
        resp = {}
        for sid in in_set[:3]:
            resp[sid] = ReadFromServer(honest, nonce, "r")
        for sid in out_set:
            resp[sid] = ReadFromServer(forged, nonce, "r")
        return resp

    client._fan_out = fake_fan_out
    result = run_return(client.execute_read_transaction(txn))
    assert result.operations[0].value == b"good"


def run_return(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=60))


def test_write_succeeds_despite_one_refusing_replica():
    # BFT liveness: one always-refusing replica (f=1, rf=4) must not block
    # writes when the other 3 (== quorum) grant consistently.
    from mochi_tpu.protocol import Grant, MultiGrant, Status, Write1RefusedFromServer

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            byz = vc.replicas[0]

            def always_refuse(req):
                mg = MultiGrant(
                    grants={
                        op.key: Grant(op.key, 0, 1, req.transaction_hash, Status.REFUSED)
                        for op in req.transaction.operations
                    },
                    client_id=req.client_id,
                    server_id=byz.server_id,
                )
                return Write1RefusedFromServer(mg, {}, req.client_id)

            byz.store.process_write1 = always_refuse
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("live-k", "live-v").build()
            )
            r = await client.execute_read_transaction(
                TransactionBuilder().read("live-k").build()
            )
            assert r.operations[0].value == b"live-v"

    run(main())


def test_write_succeeds_despite_one_skewed_timestamp_replica():
    # A replica granting at a skewed epoch must not stall writes: the client
    # picks the majority-timestamp subset.
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            byz = vc.replicas[0]
            orig = byz.store.process_write1

            def skewed(req):
                resp = orig(req)
                from mochi_tpu.protocol import Write1OkFromServer as Ok

                if isinstance(resp, Ok):
                    from dataclasses import replace as dc_replace

                    mg = resp.multi_grant
                    skewed_grants = {
                        k: dc_replace(g, timestamp=g.timestamp + 5000)
                        for k, g in mg.grants.items()
                    }
                    new_mg = dc_replace(mg, grants=skewed_grants)
                    new_mg = byz._sign_multigrant(new_mg) if hasattr(byz, "_sign_multigrant") else new_mg
                    return Ok(new_mg, resp.current_certificates)
                return resp

            byz.store.process_write1 = skewed
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("skew-k", "skew-v").build()
            )
            r = await client.execute_read_transaction(
                TransactionBuilder().read("skew-k").build()
            )
            assert r.operations[0].value == b"skew-v"

    run(main())

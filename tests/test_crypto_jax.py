"""Differential tests: JAX Ed25519 verifier vs the OpenSSL CPU path.

The TPU verifier must agree bit-for-bit with the CPU fallback on valid,
forged, and malformed inputs (SURVEY.md §7: "correctness-tested against the
CPU path"; §4 "validity bitmap on mixed valid/forged batches").  Field
arithmetic is additionally checked against python bignums.

Layout note (round 2): field elements are limbs-leading ``(17, B)`` —
batch on the trailing lane axis (see ``field.py`` module docstring).
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from mochi_tpu.crypto import batch_verify as BV
from mochi_tpu.crypto import field as F
from mochi_tpu.crypto.keys import generate_keypair, verify as cpu_verify
from mochi_tpu.verifier.spi import VerifyItem

RANGE = 1 << 255  # the limb representation covers [0, 2^255)


def _pack(ints):
    """Python ints -> limbs-leading (17, B) device array."""
    return jnp.asarray(np.stack([F.int_to_limbs(x) for x in ints], axis=-1))


class TestField:
    def _rand_pairs(self, n=8, seed=1):
        rng = random.Random(seed)
        xs = [rng.randrange(0, RANGE) for _ in range(n)]
        ys = [rng.randrange(0, RANGE) for _ in range(n)]
        return xs, ys, _pack(xs), _pack(ys)

    def _assert_mod_eq(self, got, expect_ints):
        got_ints = F.limbs_to_int_batch(np.asarray(got))
        arr = np.asarray(got)
        assert arr.min() >= 0 and arr.max() <= F.LOOSE  # loose-carry invariant
        assert [g % F.P_INT for g in got_ints] == [e % F.P_INT for e in expect_ints]

    def test_add_sub_mul(self):
        xs, ys, A, B = self._rand_pairs()
        self._assert_mod_eq(F.add(A, B), [x + y for x, y in zip(xs, ys)])
        self._assert_mod_eq(F.sub(A, B), [x - y for x, y in zip(xs, ys)])
        self._assert_mod_eq(F.mul(A, B), [x * y for x, y in zip(xs, ys)])
        self._assert_mod_eq(F.square(A), [x * x for x in xs])
        self._assert_mod_eq(F.neg(A), [-x for x in xs])
        self._assert_mod_eq(F.mul_small(A, 2), [2 * x for x in xs])
        self._assert_mod_eq(F.mul_small(A, 977), [977 * x for x in xs])

    def test_mul_skew_impls_agree(self):
        xs, ys, A, B = self._rand_pairs(seed=3)
        prev = F.SKEW_IMPL
        try:
            F.SKEW_IMPL = "reshape"
            r1 = np.asarray(F.mul(A, B))
            F.SKEW_IMPL = "shift"
            r2 = np.asarray(F.mul(A, B))
        finally:
            F.SKEW_IMPL = prev
        assert (r1 == r2).all()

    @pytest.mark.slow
    def test_pow_invert_canonical(self):
        xs, _, A, _ = self._rand_pairs(n=4, seed=2)
        p = F.P_INT
        self._assert_mod_eq(F.invert(A), [pow(x % p, p - 2, p) for x in xs])
        self._assert_mod_eq(F.pow_p58(A), [pow(x % p, (p - 5) // 8, p) for x in xs])
        can = F.limbs_to_int_batch(np.asarray(F.canonical(A)))
        assert can == [x % p for x in xs]

    def test_loose_chains_stay_bounded(self):
        """Long op chains must preserve the loose-limb invariant."""
        xs, ys, A, B = self._rand_pairs(seed=5)
        acc, acc_int = A, list(xs)
        for i in range(20):
            acc = F.mul(F.add(acc, B), F.sub(acc, A))
            acc_int = [
                ((a + y) * (a - x)) % F.P_INT
                for a, x, y in zip(acc_int, xs, ys)
            ]
            arr = np.asarray(acc)
            assert arr.min() >= 0 and arr.max() <= F.LOOSE
        self._assert_mod_eq(acc, acc_int)

    def test_edge_values(self):
        # 0, 1, p-1, p, p+17 (alias of 17), 2^255-1 (max representable)
        vals = [0, 1, F.P_INT - 1, F.P_INT, F.P_INT + 17, RANGE - 1]
        A = _pack(vals)
        can = F.limbs_to_int_batch(np.asarray(F.canonical(A)))
        assert can == [v % F.P_INT for v in vals]
        self._assert_mod_eq(F.mul(A, A), [v * v for v in vals])

    def test_int_to_limbs_rejects_oversize(self):
        with pytest.raises(AssertionError):
            F.int_to_limbs(1 << 255)


class TestBatchVerify:
    """One compiled bucket (16) exercising the full valid/forged matrix."""

    def _mixed_batch(self):
        kps = [generate_keypair() for _ in range(6)]
        items, expect = [], []
        for i, kp in enumerate(kps):
            m = f"txn-{i}".encode() * (i + 1)  # varying message lengths
            items.append(VerifyItem(kp.public_key, m, kp.sign(m)))
            expect.append(True)
        # forged: signature over a different message
        items.append(VerifyItem(kps[0].public_key, b"evil", kps[0].sign(b"good")))
        expect.append(False)
        # bit-flipped R
        s = bytearray(kps[1].sign(b"x"))
        s[3] ^= 1
        items.append(VerifyItem(kps[1].public_key, b"x", bytes(s)))
        expect.append(False)
        # bit-flipped S
        s = bytearray(kps[2].sign(b"x2"))
        s[40] ^= 1
        items.append(VerifyItem(kps[2].public_key, b"x2", bytes(s)))
        expect.append(False)
        # signed by a different key
        items.append(VerifyItem(kps[3].public_key, b"y", kps[4].sign(b"y")))
        expect.append(False)
        # non-canonical pubkey encoding (y >= p)
        items.append(VerifyItem(b"\xff" * 32, b"z", kps[0].sign(b"z")))
        expect.append(False)
        # scalar out of range (S >= L)
        sig = bytearray(kps[5].sign(b"w"))
        sig[32:] = b"\xff" * 31 + b"\x0f"
        items.append(VerifyItem(kps[5].public_key, b"w", bytes(sig)))
        expect.append(False)
        # truncated key / signature
        items.append(VerifyItem(b"\x01" * 31, b"t", kps[0].sign(b"t")))
        expect.append(False)
        items.append(VerifyItem(kps[0].public_key, b"t", b"\x02" * 63))
        expect.append(False)
        # empty message
        items.append(VerifyItem(kps[0].public_key, b"", kps[0].sign(b"")))
        expect.append(True)
        return items, expect

    def test_matches_cpu_path(self):
        items, expect = self._mixed_batch()
        got = BV.verify_batch(items)
        cpu = [
            cpu_verify(it.public_key, bytes(it.message), bytes(it.signature))
            for it in items
        ]
        assert got == expect
        assert got == cpu

    def test_empty_batch(self):
        assert BV.verify_batch([]) == []

    def test_chunked_stream_with_all_garbage_chunk(self, monkeypatch):
        """Chunked verify_batch: an all-rejected chunk inside the bounded
        launch window must skip its device launch (None in the pipeline)
        while neighboring chunks keep their verdicts — the fast path and
        the prepare-thread pipeline compose."""
        monkeypatch.setattr(BV, "MAX_BUCKET", 16)
        kp = generate_keypair()
        good = [
            VerifyItem(kp.public_key, b"c%d" % i, kp.sign(b"c%d" % i))
            for i in range(16)
        ]
        garbage = [
            VerifyItem(it.public_key, it.message, it.signature[:32] + b"\xff" * 32)
            for it in good
        ]
        stream = good + garbage + good  # 3 chunks at MAX_BUCKET=16
        before = BV.device_dispatch_count()
        out = BV.verify_batch(stream)
        assert out == [True] * 16 + [False] * 16 + [True] * 16
        assert BV.device_dispatch_count() == before + 2  # garbage chunk skipped

    def test_all_rejected_batch_skips_device(self, monkeypatch):
        """A chunk whose prechecks reject every item (garbage flood) must
        return all-False WITHOUT launching the device program — the
        no-device-amplification property scripts/forgery_bench.py measures."""
        kp = generate_keypair()
        # S >= L: canonical-length but fails the host range precheck
        garbage = [
            VerifyItem(kp.public_key, b"g%d" % i, kp.sign(b"g%d" % i)[:32] + b"\xff" * 32)
            for i in range(8)
        ]
        calls = []
        orig = BV._verify_packed_jit
        monkeypatch.setattr(
            BV, "_verify_packed_jit",
            lambda *a, **k: calls.append(1) or orig(*a, **k),
        )
        assert BV.verify_batch(garbage) == [False] * 8
        assert not calls, "device program ran on an all-rejected batch"
        # Mixed batch still goes to the device and keeps per-item verdicts
        ok_msg = b"ok"
        mixed = garbage + [VerifyItem(kp.public_key, ok_msg, kp.sign(ok_msg))]
        assert BV.verify_batch(mixed) == [False] * 8 + [True]
        assert calls
        # The skip must NOT mark the bucket compiled in the backend: the
        # next legitimate batch would then park behind a synchronous
        # 20-60 s compile (review finding, round 4).
        backend = BV.JaxBatchBackend(min_device_items=0)
        assert backend(garbage) == [False] * 8
        assert BV._bucket_size(8) not in backend._ready
        assert list(backend(mixed)) == [False] * 8 + [True]
        assert BV._bucket_size(9) in backend._ready

    def test_backend_plugs_into_spi(self):
        backend = BV.JaxBatchBackend(min_device_items=0)  # pin the device path: this test checks bucket behavior
        kp = generate_keypair()
        items = [VerifyItem(kp.public_key, b"m", kp.sign(b"m"))]
        assert list(backend(items)) == [True]

    def test_background_compile_failure_lands_in_failed(self):
        """ADVICE r1: a crash inside the background bucket compile must mark
        the bucket failed (not die with NameError and respawn threads)."""
        import threading

        backend = BV.JaxBatchBackend(min_device_items=0)  # pin the device path: this test checks bucket behavior
        backend._ready.add(16)  # pretend a small bucket is compiled
        done = threading.Event()
        orig = BV.verify_batch

        def boom(items, device=None, bucket=None):
            if bucket is None and len(items) > 16:
                raise RuntimeError("simulated compile failure")
            return orig(items, device=device, bucket=bucket)

        BV.verify_batch = boom
        try:
            kp = generate_keypair()
            items = [VerifyItem(kp.public_key, b"m", kp.sign(b"m"))] * 24
            out = backend(items)  # served chunked via bucket 16
            assert list(out) == [True] * 24
            for _ in range(100):
                with backend._lock:
                    if 32 in backend._failed and 32 not in backend._compiling:
                        done.set()
                        break
                import time

                time.sleep(0.05)
            assert done.is_set(), "failed bucket never recorded"
        finally:
            BV.verify_batch = orig

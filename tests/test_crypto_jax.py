"""Differential tests: JAX Ed25519 verifier vs the OpenSSL CPU path.

The TPU verifier must agree bit-for-bit with the CPU fallback on valid,
forged, and malformed inputs (SURVEY.md §7: "correctness-tested against the
CPU path"; §4 "validity bitmap on mixed valid/forged batches").  Field
arithmetic is additionally checked against python bignums.
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from mochi_tpu.crypto import batch_verify as BV
from mochi_tpu.crypto import field as F
from mochi_tpu.crypto.keys import generate_keypair, verify as cpu_verify
from mochi_tpu.verifier.spi import VerifyItem


class TestField:
    def _rand_pairs(self, n=8, seed=1):
        rng = random.Random(seed)
        xs = [rng.randrange(0, 1 << 256) for _ in range(n)]
        ys = [rng.randrange(0, 1 << 256) for _ in range(n)]
        A = jnp.asarray(np.stack([F.int_to_limbs(x) for x in xs]))
        B = jnp.asarray(np.stack([F.int_to_limbs(y) for y in ys]))
        return xs, ys, A, B

    def _assert_mod_eq(self, got, expect_ints):
        got_ints = F.limbs_to_int_batch(np.asarray(got))
        arr = np.asarray(got)
        assert arr.min() >= 0 and arr.max() <= F.MASK  # loose-reduction invariant
        assert [g % F.P_INT for g in got_ints] == [e % F.P_INT for e in expect_ints]

    def test_add_sub_mul(self):
        xs, ys, A, B = self._rand_pairs()
        self._assert_mod_eq(F.add(A, B), [x + y for x, y in zip(xs, ys)])
        self._assert_mod_eq(F.sub(A, B), [x - y for x, y in zip(xs, ys)])
        self._assert_mod_eq(F.mul(A, B), [x * y for x, y in zip(xs, ys)])
        self._assert_mod_eq(F.square(A), [x * x for x in xs])
        self._assert_mod_eq(F.neg(A), [-x for x in xs])

    def test_pow_invert_canonical(self):
        xs, _, A, _ = self._rand_pairs(n=4, seed=2)
        p = F.P_INT
        self._assert_mod_eq(F.invert(A), [pow(x % p, p - 2, p) for x in xs])
        self._assert_mod_eq(F.pow_p58(A), [pow(x % p, (p - 5) // 8, p) for x in xs])
        can = F.limbs_to_int_batch(np.asarray(F.canonical(A)))
        assert can == [x % p for x in xs]

    def test_edge_values(self):
        # 0, 1, p-1, p, 2p (aliases of 0), 2^256-1
        vals = [0, 1, F.P_INT - 1, F.P_INT, 2 * F.P_INT, (1 << 256) - 1]
        A = jnp.asarray(np.stack([F.int_to_limbs(v) for v in vals]))
        can = F.limbs_to_int_batch(np.asarray(F.canonical(A)))
        assert can == [v % F.P_INT for v in vals]
        self._assert_mod_eq(F.mul(A, A), [v * v for v in vals])


class TestBatchVerify:
    """One compiled bucket (16) exercising the full valid/forged matrix."""

    def _mixed_batch(self):
        kps = [generate_keypair() for _ in range(6)]
        items, expect = [], []
        for i, kp in enumerate(kps):
            m = f"txn-{i}".encode() * (i + 1)  # varying message lengths
            items.append(VerifyItem(kp.public_key, m, kp.sign(m)))
            expect.append(True)
        # forged: signature over a different message
        items.append(VerifyItem(kps[0].public_key, b"evil", kps[0].sign(b"good")))
        expect.append(False)
        # bit-flipped R
        s = bytearray(kps[1].sign(b"x"))
        s[3] ^= 1
        items.append(VerifyItem(kps[1].public_key, b"x", bytes(s)))
        expect.append(False)
        # bit-flipped S
        s = bytearray(kps[2].sign(b"x2"))
        s[40] ^= 1
        items.append(VerifyItem(kps[2].public_key, b"x2", bytes(s)))
        expect.append(False)
        # signed by a different key
        items.append(VerifyItem(kps[3].public_key, b"y", kps[4].sign(b"y")))
        expect.append(False)
        # non-canonical pubkey encoding (y >= p)
        items.append(VerifyItem(b"\xff" * 32, b"z", kps[0].sign(b"z")))
        expect.append(False)
        # scalar out of range (S >= L)
        sig = bytearray(kps[5].sign(b"w"))
        sig[32:] = b"\xff" * 31 + b"\x0f"
        items.append(VerifyItem(kps[5].public_key, b"w", bytes(sig)))
        expect.append(False)
        # truncated key / signature
        items.append(VerifyItem(b"\x01" * 31, b"t", kps[0].sign(b"t")))
        expect.append(False)
        items.append(VerifyItem(kps[0].public_key, b"t", b"\x02" * 63))
        expect.append(False)
        # empty message
        items.append(VerifyItem(kps[0].public_key, b"", kps[0].sign(b"")))
        expect.append(True)
        return items, expect

    def test_matches_cpu_path(self):
        items, expect = self._mixed_batch()
        got = BV.verify_batch(items)
        cpu = [
            cpu_verify(it.public_key, bytes(it.message), bytes(it.signature))
            for it in items
        ]
        assert got == expect
        assert got == cpu

    def test_empty_batch(self):
        assert BV.verify_batch([]) == []

    def test_backend_plugs_into_spi(self):
        backend = BV.JaxBatchBackend()
        kp = generate_keypair()
        items = [VerifyItem(kp.public_key, b"m", kp.sign(b"m"))]
        assert list(backend(items)) == [True]

"""Native (_mcode C extension) vs pure-Python codec: differential parity.

The wire format doubles as the signing format, so the two implementations
must agree bit-for-bit on encode and verdict-for-verdict on decode errors —
a native/Python disagreement would let a message verify on one replica and
fail on another (same BFT-divergence class as the verifier parity tests).
"""

import random
import string

import pytest

from mochi_tpu.native import get_mcode
from mochi_tpu.protocol import codec

native = get_mcode()
pytestmark = pytest.mark.skipif(native is None, reason="no C toolchain")


def _rand_value(rng, depth=0):
    t = rng.randrange(9 if depth < 3 else 6)
    if t == 0:
        return None
    if t == 1:
        return rng.choice([True, False])
    if t == 2:
        return rng.randrange(0, 1 << 64)
    if t == 3:
        return -rng.randrange(1, 1 << 63)
    if t == 4:
        return bytes(rng.randrange(0, 40))
    if t == 5:
        alphabet = string.printable + "λ中☃"
        return "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 20)))
    if t == 6:
        return [_rand_value(rng, depth + 1) for _ in range(rng.randrange(0, 6))]
    return {
        "".join(rng.choice("abcde中λ") for _ in range(rng.randrange(1, 8))): _rand_value(
            rng, depth + 1
        )
        for _ in range(rng.randrange(0, 6))
    }


def test_encode_bit_identical_fuzz():
    rng = random.Random(4242)
    for _ in range(1500):
        v = _rand_value(rng)
        e_py = codec._encode_py(v)
        e_c = native.encode(v)
        assert e_py == e_c, v
        assert native.decode(e_c) == codec._decode_py(e_py)


def test_decode_error_parity():
    bad_inputs = [
        b"",
        b"\xff",
        b"\x03",  # truncated varint
        b"\x05\x05ab",  # truncated bytes
        b"\x00\x00",  # trailing
        b"\x08\x01\x03\x01\x00",  # dict key not str
        b"\x07\xff\xff\xff\xff\x7f",  # list guard
        b"\x03" + b"\x80" * 10 + b"\x02",  # varint out of 64-bit range
    ]
    for bad in bad_inputs:
        with pytest.raises(ValueError):
            native.decode(bad)
        with pytest.raises(ValueError):
            codec._decode_py(bad)


def test_encode_type_error_parity():
    for v in [2**64, -(2**64) - 1, {1: "x"}, object(), 1.5]:
        with pytest.raises(TypeError):
            native.encode(v)
        with pytest.raises(TypeError):
            codec._encode_py(v)


def test_deep_nesting_guard_parity():
    v = None
    for _ in range(40):
        v = [v]
    with pytest.raises(ValueError):
        native.encode(v)
    with pytest.raises(ValueError):
        codec._encode_py(v)


def test_bound_codec_is_native_when_available():
    assert codec.encode is native.encode
    assert codec.decode is native.decode

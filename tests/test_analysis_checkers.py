"""Per-checker tests for mochi_tpu.analysis, driven by good/bad fixture
pairs under tests/analysis_fixtures/ (the bad file of each pair is also the
seeded-regression corpus tests/test_static_analysis.py runs through the
CLI)."""

import os

import pytest

from mochi_tpu.analysis import core

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def run_rule(rule: str, filename: str) -> core.RunResult:
    # scoped=False: fixtures live under tests/, outside the production path
    # scopes (e.g. trace-safety only looks at crypto/ + parallel/).
    return core.run([fixture(filename)], rules=[rule], scoped=False)


BAD_EXPECTATIONS = [
    ("async-blocking", "async_blocking_bad.py", 4),
    ("cancellation-hygiene", "cancellation_bad.py", 4),
    ("jax-trace-safety", "trace_safety_bad.py", 5),
    ("constant-time", "const_time_bad.py", 4),
    ("protocol-invariants", "invariants_bad.py", 2),
]


@pytest.mark.parametrize("rule,filename,expected", BAD_EXPECTATIONS)
def test_bad_fixture_trips_checker(rule, filename, expected):
    result = run_rule(rule, filename)
    lines = sorted(f.line for f in result.new)
    assert len(result.new) == expected, (
        f"{filename}: expected {expected} findings, got "
        f"{[f.render() for f in result.new]}"
    )
    assert all(f.rule == rule for f in result.new)
    assert len(set(lines)) == expected, "each seeded site flags exactly once"


@pytest.mark.parametrize(
    "rule,filename",
    [
        ("async-blocking", "async_blocking_good.py"),
        ("cancellation-hygiene", "cancellation_good.py"),
        ("jax-trace-safety", "trace_safety_good.py"),
        ("constant-time", "const_time_good.py"),
        ("protocol-invariants", "invariants_good.py"),
    ],
)
def test_good_fixture_is_clean(rule, filename):
    result = run_rule(rule, filename)
    assert result.new == [], [f.render() for f in result.new]


def test_cross_rule_runs_do_not_bleed():
    # The cancellation fixture must not trip e.g. constant-time, and running
    # every rule over a bad fixture still only reports its own rule's sites.
    result = core.run(
        [fixture("cancellation_bad.py")], scoped=False
    )
    assert {f.rule for f in result.new} == {"cancellation-hygiene"}


# ------------------------------------------------------------- suppressions


def test_suppression_same_line_and_line_above():
    result = core.run([fixture("suppression_fixture.py")], scoped=False)
    assert len(result.new) == 1, [f.render() for f in result.new]
    assert len(result.suppressed) == 2
    # the live finding is the `time.sleep` inside live_violation(), the
    # un-commented third coroutine — not either suppressed site
    src_lines = open(fixture("suppression_fixture.py")).read().splitlines()
    live_def = next(
        i for i, ln in enumerate(src_lines, start=1) if "def live_violation" in ln
    )
    assert result.new[0].line > live_def
    assert result.new[0].snippet == "time.sleep(0.1)"
    assert all(s.line < live_def for s in result.suppressed)


def test_suppression_requires_matching_rule(tmp_path):
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # mochi-lint: disable=constant-time\n"
    )
    p = tmp_path / "wrong_rule.py"
    p.write_text(src)
    result = core.run([str(p)], scoped=False)
    assert len(result.new) == 1  # suppression names a different rule

    p2 = tmp_path / "all_rule.py"
    p2.write_text(src.replace("constant-time", "all"))
    result = core.run([str(p2)], scoped=False)
    assert result.new == [] and len(result.suppressed) == 1


# ----------------------------------------------------------------- baseline


def test_baseline_grandfathers_and_ratchets(tmp_path):
    target = fixture("async_blocking_bad.py")
    first = core.run([target], scoped=False)
    assert len(first.new) == 4

    baseline_path = tmp_path / "baseline.json"
    core.write_baseline(str(baseline_path), first.new)

    second = core.run([target], scoped=False, baseline=str(baseline_path))
    assert second.new == []
    assert len(second.baselined) == 4

    # a NEW violation is still caught even with the old ones baselined
    extra = tmp_path / "extra.py"
    extra.write_text("import time\nasync def g():\n    time.sleep(2)\n")
    third = core.run(
        [target, str(extra)], scoped=False, baseline=str(baseline_path)
    )
    assert len(third.new) == 1 and third.new[0].path.endswith("extra.py")
    assert len(third.baselined) == 4


def test_fingerprint_survives_line_drift(tmp_path):
    a = tmp_path / "a.py"
    a.write_text("import time\nasync def f():\n    time.sleep(1)\n")
    fp1 = core.run([str(a)], scoped=False).new[0].fingerprint
    # prepend unrelated code: the finding moves lines but not content
    a.write_text("import time\nX = 1\nY = 2\nasync def f():\n    time.sleep(1)\n")
    fp2 = core.run([str(a)], scoped=False).new[0].fingerprint
    assert fp1 == fp2


# -------------------------------------------------------------- odds & ends


def test_raise_in_nested_def_does_not_count_as_reraise(tmp_path):
    # A handler whose only `raise` lives inside a nested function never
    # re-raises in the handler itself — it still swallows CancelledError.
    p = tmp_path / "nested_raise.py"
    p.write_text(
        "async def f(ch):\n"
        "    try:\n"
        "        await ch.get()\n"
        "    except BaseException:\n"
        "        def _log():\n"
        "            raise RuntimeError('later')\n"
        "        register(_log)\n"
    )
    result = core.run([str(p)], rules=["cancellation-hygiene"], scoped=False)
    assert len(result.new) == 1, [f.render() for f in result.new]


def test_local_name_collision_not_flagged(tmp_path):
    # A module-local function whose bare name collides with a deny-list
    # pattern's terminal segment (os.wait, crypto.keys.verify, ...) is NOT a
    # blocking call — single-segment names only match single-segment patterns.
    p = tmp_path / "local_names.py"
    p.write_text(
        "def wait(handles):\n    return handles\n"
        "def verify(x):\n    return x\n"
        "async def f():\n    return wait(verify(1))\n"
    )
    result = core.run([str(p)], rules=["async-blocking"], scoped=False)
    assert result.new == [], [f.render() for f in result.new]


def test_fingerprints_stable_across_cwd(tmp_path, monkeypatch):
    # lint.sh scans from the repo root; standing_rules.py passes absolute
    # paths from an arbitrary CWD — fingerprints must agree or a non-empty
    # baseline silently stops matching.
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("import time\nasync def f():\n    time.sleep(1)\n")

    monkeypatch.chdir(tmp_path)
    fp_rel = core.run(["pkg"], scoped=False).new[0]
    monkeypatch.chdir("/")
    fp_abs = core.run([str(pkg)], scoped=False).new[0]
    assert fp_rel.path == fp_abs.path == "pkg/mod.py"
    assert fp_rel.fingerprint == fp_abs.fingerprint


def test_single_file_scan_keeps_package_path():
    # Scanning one file must behave exactly like the directory scan that
    # contains it: `analysis mochi_tpu/cluster/config.py` once reported a
    # false-positive protocol-invariants finding (basename display dropped
    # the cluster/config.py exemption), and `analysis mochi_tpu/crypto/keys.py`
    # silently skipped the crypto-scoped checkers.
    import mochi_tpu

    pkg_root = os.path.dirname(os.path.dirname(mochi_tpu.__file__))
    cfg = os.path.join(pkg_root, "mochi_tpu", "cluster", "config.py")
    result = core.run([cfg], rules=["protocol-invariants"], scoped=True)
    assert result.new == [], [f.render() for f in result.new]
    keys = os.path.join(pkg_root, "mochi_tpu", "crypto", "keys.py")
    (disp,) = [d for d, _ in core.iter_python_files([keys])]
    assert disp == "mochi_tpu/crypto/keys.py"


def test_identical_snippets_get_distinct_fingerprints(tmp_path):
    p = tmp_path / "twice.py"
    p.write_text(
        "import time\n"
        "async def f():\n    time.sleep(1)\n"
        "async def g():\n    time.sleep(1)\n"
    )
    result = core.run([str(p)], scoped=False)
    assert len(result.new) == 2
    fps = {f.fingerprint for f in result.new}
    assert len(fps) == 2, "one baseline entry must not grandfather both sites"


def test_parse_error_is_a_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    result = core.run([str(p)], scoped=False)
    assert len(result.new) == 1 and result.new[0].rule == "parse-error"


def test_unknown_rule_rejected():
    with pytest.raises(ValueError):
        core.run([FIXTURES], rules=["no-such-rule"])


def test_scoping_excludes_fixture_paths():
    # With default scoping, trace-safety ignores files outside crypto/ and
    # parallel/ — the reason fixture tests pass scoped=False.
    result = core.run(
        [fixture("trace_safety_bad.py")], rules=["jax-trace-safety"], scoped=True
    )
    assert result.new == []

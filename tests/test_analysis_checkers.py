"""Per-checker tests for mochi_tpu.analysis, driven by good/bad fixture
pairs under tests/analysis_fixtures/ (the bad file of each pair is also the
seeded-regression corpus tests/test_static_analysis.py runs through the
CLI)."""

import os

import pytest

from mochi_tpu.analysis import core

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def run_rule(rule: str, filename: str) -> core.RunResult:
    # scoped=False: fixtures live under tests/, outside the production path
    # scopes (e.g. trace-safety only looks at crypto/ + parallel/).
    return core.run([fixture(filename)], rules=[rule], scoped=False)


BAD_EXPECTATIONS = [
    ("async-blocking", "async_blocking_bad.py", 4),
    ("cancellation-hygiene", "cancellation_bad.py", 4),
    ("jax-trace-safety", "trace_safety_bad.py", 5),
    ("constant-time", "const_time_bad.py", 4),
    ("protocol-invariants", "invariants_bad.py", 2),
    ("await-races", "await_races_bad.py", 5),
    ("native-const-time", "native_ct_bad.c", 4),
    ("span-lazy-label", "span_lazy_bad.py", 4),
    ("wire-taint", "wire_taint_bad.py", 5),
    ("unbounded-growth", "unbounded_growth_bad.py", 4),
]


@pytest.mark.parametrize("rule,filename,expected", BAD_EXPECTATIONS)
def test_bad_fixture_trips_checker(rule, filename, expected):
    result = run_rule(rule, filename)
    lines = sorted(f.line for f in result.new)
    assert len(result.new) == expected, (
        f"{filename}: expected {expected} findings, got "
        f"{[f.render() for f in result.new]}"
    )
    assert all(f.rule == rule for f in result.new)
    assert len(set(lines)) == expected, "each seeded site flags exactly once"


@pytest.mark.parametrize(
    "rule,filename",
    [
        ("async-blocking", "async_blocking_good.py"),
        ("cancellation-hygiene", "cancellation_good.py"),
        ("jax-trace-safety", "trace_safety_good.py"),
        ("constant-time", "const_time_good.py"),
        ("protocol-invariants", "invariants_good.py"),
        ("await-races", "await_races_good.py"),
        ("native-const-time", "native_ct_good.c"),
        ("span-lazy-label", "span_lazy_good.py"),
        ("wire-taint", "wire_taint_good.py"),
        ("unbounded-growth", "unbounded_growth_good.py"),
    ],
)
def test_good_fixture_is_clean(rule, filename):
    result = run_rule(rule, filename)
    assert result.new == [], [f.render() for f in result.new]


def test_cross_rule_runs_do_not_bleed():
    # The cancellation fixture must not trip e.g. constant-time, and running
    # every rule over a bad fixture still only reports its own rule's sites.
    result = core.run(
        [fixture("cancellation_bad.py")], scoped=False
    )
    assert {f.rule for f in result.new} == {"cancellation-hygiene"}


# ------------------------------------------------------------- suppressions


def test_suppression_same_line_and_line_above():
    result = core.run([fixture("suppression_fixture.py")], scoped=False)
    assert len(result.new) == 1, [f.render() for f in result.new]
    assert len(result.suppressed) == 2
    # the live finding is the `time.sleep` inside live_violation(), the
    # un-commented third coroutine — not either suppressed site
    src_lines = open(fixture("suppression_fixture.py")).read().splitlines()
    live_def = next(
        i for i, ln in enumerate(src_lines, start=1) if "def live_violation" in ln
    )
    assert result.new[0].line > live_def
    assert result.new[0].snippet == "time.sleep(0.1)"
    assert all(s.line < live_def for s in result.suppressed)


def test_suppression_requires_matching_rule(tmp_path):
    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # mochi-lint: disable=constant-time\n"
    )
    p = tmp_path / "wrong_rule.py"
    p.write_text(src)
    result = core.run([str(p)], scoped=False)
    assert len(result.new) == 1  # suppression names a different rule

    p2 = tmp_path / "all_rule.py"
    p2.write_text(src.replace("constant-time", "all"))
    result = core.run([str(p2)], scoped=False)
    assert result.new == [] and len(result.suppressed) == 1


# ----------------------------------------------------------------- baseline


def test_baseline_grandfathers_and_ratchets(tmp_path):
    target = fixture("async_blocking_bad.py")
    first = core.run([target], scoped=False)
    assert len(first.new) == 4

    baseline_path = tmp_path / "baseline.json"
    core.write_baseline(str(baseline_path), first.new)

    second = core.run([target], scoped=False, baseline=str(baseline_path))
    assert second.new == []
    assert len(second.baselined) == 4

    # a NEW violation is still caught even with the old ones baselined
    extra = tmp_path / "extra.py"
    extra.write_text("import time\nasync def g():\n    time.sleep(2)\n")
    third = core.run(
        [target, str(extra)], scoped=False, baseline=str(baseline_path)
    )
    assert len(third.new) == 1 and third.new[0].path.endswith("extra.py")
    assert len(third.baselined) == 4


def test_fingerprint_survives_line_drift(tmp_path):
    a = tmp_path / "a.py"
    a.write_text("import time\nasync def f():\n    time.sleep(1)\n")
    fp1 = core.run([str(a)], scoped=False).new[0].fingerprint
    # prepend unrelated code: the finding moves lines but not content
    a.write_text("import time\nX = 1\nY = 2\nasync def f():\n    time.sleep(1)\n")
    fp2 = core.run([str(a)], scoped=False).new[0].fingerprint
    assert fp1 == fp2


# -------------------------------------------------------------- odds & ends


def test_raise_in_nested_def_does_not_count_as_reraise(tmp_path):
    # A handler whose only `raise` lives inside a nested function never
    # re-raises in the handler itself — it still swallows CancelledError.
    p = tmp_path / "nested_raise.py"
    p.write_text(
        "async def f(ch):\n"
        "    try:\n"
        "        await ch.get()\n"
        "    except BaseException:\n"
        "        def _log():\n"
        "            raise RuntimeError('later')\n"
        "        register(_log)\n"
    )
    result = core.run([str(p)], rules=["cancellation-hygiene"], scoped=False)
    assert len(result.new) == 1, [f.render() for f in result.new]


def test_local_name_collision_not_flagged(tmp_path):
    # A module-local function whose bare name collides with a deny-list
    # pattern's terminal segment (os.wait, crypto.keys.verify, ...) is NOT a
    # blocking call — single-segment names only match single-segment patterns.
    p = tmp_path / "local_names.py"
    p.write_text(
        "def wait(handles):\n    return handles\n"
        "def verify(x):\n    return x\n"
        "async def f():\n    return wait(verify(1))\n"
    )
    result = core.run([str(p)], rules=["async-blocking"], scoped=False)
    assert result.new == [], [f.render() for f in result.new]


def test_fingerprints_stable_across_cwd(tmp_path, monkeypatch):
    # lint.sh scans from the repo root; standing_rules.py passes absolute
    # paths from an arbitrary CWD — fingerprints must agree or a non-empty
    # baseline silently stops matching.
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("import time\nasync def f():\n    time.sleep(1)\n")

    monkeypatch.chdir(tmp_path)
    fp_rel = core.run(["pkg"], scoped=False).new[0]
    monkeypatch.chdir("/")
    fp_abs = core.run([str(pkg)], scoped=False).new[0]
    assert fp_rel.path == fp_abs.path == "pkg/mod.py"
    assert fp_rel.fingerprint == fp_abs.fingerprint


def test_single_file_scan_keeps_package_path():
    # Scanning one file must behave exactly like the directory scan that
    # contains it: `analysis mochi_tpu/cluster/config.py` once reported a
    # false-positive protocol-invariants finding (basename display dropped
    # the cluster/config.py exemption), and `analysis mochi_tpu/crypto/keys.py`
    # silently skipped the crypto-scoped checkers.
    import mochi_tpu

    pkg_root = os.path.dirname(os.path.dirname(mochi_tpu.__file__))
    cfg = os.path.join(pkg_root, "mochi_tpu", "cluster", "config.py")
    result = core.run([cfg], rules=["protocol-invariants"], scoped=True)
    assert result.new == [], [f.render() for f in result.new]
    keys = os.path.join(pkg_root, "mochi_tpu", "crypto", "keys.py")
    (disp,) = [d for d, _ in core.iter_python_files([keys])]
    assert disp == "mochi_tpu/crypto/keys.py"


def test_identical_snippets_get_distinct_fingerprints(tmp_path):
    p = tmp_path / "twice.py"
    p.write_text(
        "import time\n"
        "async def f():\n    time.sleep(1)\n"
        "async def g():\n    time.sleep(1)\n"
    )
    result = core.run([str(p)], scoped=False)
    assert len(result.new) == 2
    fps = {f.fingerprint for f in result.new}
    assert len(fps) == 2, "one baseline entry must not grandfather both sites"


def test_parse_error_is_a_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    result = core.run([str(p)], scoped=False)
    assert len(result.new) == 1 and result.new[0].rule == "parse-error"


def test_unknown_rule_rejected():
    with pytest.raises(ValueError):
        core.run([FIXTURES], rules=["no-such-rule"])


def test_scoping_excludes_fixture_paths():
    # With default scoping, trace-safety ignores files outside crypto/ and
    # parallel/ — the reason fixture tests pass scoped=False.
    result = core.run(
        [fixture("trace_safety_bad.py")], rules=["jax-trace-safety"], scoped=True
    )
    assert result.new == []


# ------------------------------------------------- await-races: tiers & sites


def test_await_races_severity_tiers_and_subrules():
    result = run_rule("await-races", "await_races_bad.py")
    by_kind = {f.message.split("]")[0].lstrip("["): f for f in result.new}
    assert set(by_kind) == {
        "check-then-act", "stale-read", "shared-iter", "tally-authority"
    }
    assert by_kind["check-then-act"].severity == "high"
    assert by_kind["tally-authority"].severity == "high"
    assert by_kind["stale-read"].severity == "medium"
    assert by_kind["shared-iter"].severity == "medium"
    # tier shows in the rendering but NOT in the fingerprint (re-tiering a
    # rule must not invalidate baselines)
    assert "/high" in by_kind["check-then-act"].render()
    from dataclasses import replace

    retiered = replace(by_kind["check-then-act"], severity="advice")
    assert retiered.fingerprint == by_kind["check-then-act"].fingerprint


def test_await_races_constructor_call_does_not_taint_local(tmp_path):
    # Binding from a call that merely TAKES an element read builds a new
    # value — the first dry run flagged `self._new_replica(self.config
    # .servers[k].host)` shapes tree-wide and drowned the real findings.
    p = tmp_path / "ctor.py"
    p.write_text(
        "import asyncio\n"
        "class C:\n"
        "    async def f(self, k):\n"
        "        fresh = self.build(self.servers[k].host)\n"
        "        await asyncio.sleep(0)\n"
        "        return fresh\n"
    )
    result = core.run([str(p)], rules=["await-races"], scoped=False)
    assert result.new == [], [f.render() for f in result.new]


def test_await_races_slice_of_id_not_tracked(tmp_path):
    # self.client_id[:8] slices an immutable id — not an element read
    p = tmp_path / "slice.py"
    p.write_text(
        "import asyncio\n"
        "class C:\n"
        "    async def f(self):\n"
        "        tag = [f'{self.client_id[:8]}-{j}' for j in range(4)]\n"
        "        await asyncio.sleep(0)\n"
        "        return tag\n"
    )
    result = core.run([str(p)], rules=["await-races"], scoped=False)
    assert result.new == [], [f.render() for f in result.new]


def test_await_races_lock_detection_is_word_level(tmp_path):
    """`with self._lock:` clears a check-then-act; `with self.clock():`
    and `with self.blocking_io():` must NOT — the substring "lock" inside
    an unrelated word would silently disable the highest-severity rule
    for the whole block."""
    template = (
        "import asyncio\n"
        "class C:\n"
        "    async def f(self, k):\n"
        "        if k in self.table:\n"
        "            await asyncio.sleep(0)\n"
        "            with {ctx}:\n"
        "                del self.table[k]\n"
    )
    for ctx, cleared in (
        ("self._lock", True),
        ("self.session_locks[k]", True),
        ("self.clock()", False),
        ("self.blocking_io()", False),
    ):
        p = tmp_path / "lockcase.py"
        p.write_text(template.format(ctx=ctx))
        result = core.run([str(p)], rules=["await-races"], scoped=False)
        if cleared:
            assert result.new == [], (ctx, [f.render() for f in result.new])
        else:
            assert any(
                "check-then-act" in f.message for f in result.new
            ), (ctx, [f.render() for f in result.new])


# --------------------------------------------------------- hygiene & native


def test_unused_suppression_is_a_finding(tmp_path):
    p = tmp_path / "stale_supp.py"
    p.write_text(
        "import asyncio\n"
        "# mochi-lint: disable=async-blocking -- nothing here needs this\n"
        "async def f():\n"
        "    await asyncio.sleep(0)\n"
    )
    result = core.run([str(p)], scoped=False, hygiene=True)
    assert len(result.new) == 1
    assert result.new[0].rule == core.HYGIENE_RULE
    assert "unused suppression" in result.new[0].message
    # without hygiene the same tree passes (rule-subset runs must not
    # convict suppressions the skipped checkers could have vindicated)
    assert core.run([str(p)], scoped=False).new == []


def test_stale_baseline_entry_is_a_finding(tmp_path):
    target = tmp_path / "clean.py"
    target.write_text("import asyncio\nasync def f():\n    await asyncio.sleep(0)\n")
    baseline = tmp_path / "baseline.json"
    import json

    disp = core.display_path(str(target))
    baseline.write_text(
        json.dumps({"fingerprints": ["deadbeefdeadbeef"], "paths": [disp]})
    )
    result = core.run(
        [str(target)], scoped=False, baseline=str(baseline), hygiene=True
    )
    assert len(result.new) == 1
    assert result.new[0].rule == core.HYGIENE_RULE
    assert "stale baseline entry deadbeefdeadbeef" in result.new[0].message


def test_stale_baseline_needs_coverage_to_convict(tmp_path):
    """A partial-path run must NOT convict baseline entries it couldn't
    have matched (the entry may belong to an unscanned file — convicting
    it, and the --write-baseline advice in the message, would silently
    amnesty every unscanned file's grandfathered debt).  Coverage comes
    from the ``paths`` record --write-baseline stores; a legacy baseline
    without one never convicts."""
    import json

    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    for p in (a, b):
        p.write_text("import asyncio\nasync def f():\n    await asyncio.sleep(0)\n")
    baseline = tmp_path / "baseline.json"
    # entry recorded against BOTH files: scanning only b.py is not coverage
    baseline.write_text(
        json.dumps(
            {
                "fingerprints": ["deadbeefdeadbeef"],
                "paths": [core.display_path(str(a)), core.display_path(str(b))],
            }
        )
    )
    partial = core.run(
        [str(b)], scoped=False, baseline=str(baseline), hygiene=True
    )
    assert partial.new == []
    # legacy baseline (no paths record): staleness is undecidable — silent
    baseline.write_text(json.dumps({"fingerprints": ["deadbeefdeadbeef"]}))
    legacy = core.run(
        [str(a), str(b)], scoped=False, baseline=str(baseline), hygiene=True
    )
    assert legacy.new == []


def test_write_baseline_records_scanned_paths(tmp_path):
    target = fixture("async_blocking_bad.py")
    first = core.run([target], scoped=False)
    assert first.new
    baseline_path = tmp_path / "baseline.json"
    core.write_baseline(str(baseline_path), first.new, scanned=first.scanned)
    assert core.load_baseline_paths(str(baseline_path)) == set(first.scanned)
    # the round trip convicts nothing (all entries still match) and a
    # removed finding WOULD convict: full coverage is satisfied
    again = core.run(
        [target], scoped=False, baseline=str(baseline_path), hygiene=True
    )
    assert again.new == [] and len(again.baselined) == len(first.new)


def test_suppression_justification_does_not_bleed_into_rules(tmp_path):
    # `disable=<rule> -- why` must suppress <rule>; the prose after the
    # rule list once bled into the parsed rule names and disabled nothing
    p = tmp_path / "justified.py"
    p.write_text(
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # mochi-lint: disable=async-blocking -- justified: fixture\n"
    )
    result = core.run([str(p)], scoped=False, hygiene=True)
    assert result.new == [], [f.render() for f in result.new]
    assert len(result.suppressed) == 1


def test_native_hbatch_sign_path_pinned_clean():
    # The REAL engine is the known-good fixture: ge_mul_base is annotated
    # `mochi-ct: secret(k)` and must scan clean apart from the one reviewed
    # comb-table suppression — which must be load-bearing (hygiene would
    # flag it as unused otherwise).
    import mochi_tpu

    native = os.path.join(
        os.path.dirname(mochi_tpu.__file__), "native", "hbatch.c"
    )
    result = core.run([native], rules=["native-const-time"], scoped=True)
    assert result.new == [], [f.render() for f in result.new]

    full = core.run([native], hygiene=True)
    assert full.new == [], [f.render() for f in full.new]
    assert len(full.suppressed) == 1  # the BCOMB secret-index site


def test_native_ct_compound_assignment_taints(tmp_path):
    """`d |= k[0]` must taint `d` like `d = k[0]` does — accumulate-into
    is THE dominant constant-time C idiom, and missing it silently
    un-flags the secret branch on the accumulator.  Comparisons must not
    false-taint."""
    p = tmp_path / "acc.c"
    p.write_text(
        "/* mochi-ct: secret(k) */\n"
        "static int acc(const unsigned char k[32]) {\n"
        "    int d = 0;\n"
        "    d |= k[0];\n"
        "    if (d) { return 1; }\n"
        "    int clean = 0;\n"
        "    int cmp = (clean == 0);\n"
        "    if (cmp) { return 2; }\n"
        "    return 0;\n"
        "}\n"
    )
    result = core.run([str(p)], rules=["native-const-time"], scoped=False)
    branch = [f for f in result.new if "secret-branch" in f.message]
    assert len(branch) == 1, [f.render() for f in result.new]
    assert branch[0].line == 5  # `if (d)` — not the cmp branch


def test_await_races_mutating_call_kwarg_await_is_boundary(tmp_path):
    """An await inside a KEYWORD argument of a mutating call is a segment
    boundary like any positional-arg await — skipping it corrupted segment
    numbering and silently suppressed every sub-rule downstream."""
    p = tmp_path / "kw.py"
    p.write_text(
        "class C:\n"
        "    async def f(self, k):\n"
        "        v = self.table[k]\n"
        "        self.stats.update(extra=await self.fetch())\n"
        "        return v\n"
    )
    result = core.run([str(p)], rules=["await-races"], scoped=False)
    assert len(result.new) == 1, [f.render() for f in result.new]
    assert "stale" in result.new[0].message
    assert result.new[0].line == 5  # the post-await use of `v`


def test_await_races_augassign_reads_stale_local(tmp_path):
    """`n += 1` LOADS n before the store: a tracked element read used this
    way after an await is exactly the read-modify-write of stale state the
    rule exists for."""
    p = tmp_path / "aug.py"
    p.write_text(
        "import asyncio\n"
        "class C:\n"
        "    async def f(self, k):\n"
        "        n = self.counts[k]\n"
        "        await asyncio.sleep(0)\n"
        "        n += 1\n"
        "        return n\n"
    )
    result = core.run([str(p)], rules=["await-races"], scoped=False)
    assert len(result.new) == 1, [f.render() for f in result.new]
    assert "stale" in result.new[0].message
    assert result.new[0].line == 6  # the augmented load, not the return


def test_native_ct_two_line_header_scanned(tmp_path):
    """A function whose name sits on the line AFTER its return type (the
    GNU/kernel style) must scan like a single-line header — it used to
    bypass the checker entirely."""
    p = tmp_path / "two.c"
    p.write_text(
        "/* mochi-ct: secret(k) */\n"
        "static void\n"
        "two_line(const unsigned char k[32], unsigned char *out) {\n"
        "    if (k[0]) {\n"
        "        out[0] = 1;\n"
        "    }\n"
        "}\n"
    )
    result = core.run([str(p)], rules=["native-const-time"], scoped=False)
    branch = [f for f in result.new if "secret-branch" in f.message]
    assert len(branch) == 1, [f.render() for f in result.new]
    assert branch[0].line == 4


def test_native_hbatch_checker_not_vacuous(tmp_path):
    # Strip the reviewed suppression from the real file: the comb-table
    # lookup must then flag — proving the annotation + taint actually
    # reach the hot site (the pin isn't a scope accident).
    import mochi_tpu

    native = os.path.join(
        os.path.dirname(mochi_tpu.__file__), "native", "hbatch.c"
    )
    src = open(native).read()
    stripped = "\n".join(
        ln for ln in src.splitlines() if "mochi-lint" not in ln
    )
    tree = tmp_path / "native"
    tree.mkdir()
    (tree / "hbatch.c").write_text(stripped)
    result = core.run([str(tree / "hbatch.c")], rules=["native-const-time"], scoped=False)
    assert len(result.new) == 1, [f.render() for f in result.new]
    assert "BCOMB" in result.new[0].snippet
    assert result.new[0].severity == "advice"

"""Tier-1 pins for the deterministic scenario engine (round 16).

What is pinned, and why it is the contract:

* **spec-draw + run determinism** — same seed ⇒ identical drawn spec ⇒
  byte-identical canonical record ×3 (spec, executed schedule, acked map,
  invariant verdict).  This is what makes a failing seed a REPRODUCTION.
* **one small end-to-end scenario per fault family** — crash+restart
  (durable WAL replay), partition+heal, Byzantine replica, Byzantine
  client, load spike, live reconfig, and SIGKILL-on-real-processes —
  each with the invariant verdict held and family-specific evidence
  asserted (so a family silently degenerating to a no-op fails here).
* **the violation arc** — an injected store-level conflicting commit is
  DETECTED, flight-dumped with the scenario seed stamped in, REPLAYED
  byte-identically from the seed alone (the dump's stamp regenerates the
  identical spec hash), and MINIMIZED to a strictly smaller spec that
  still reproduces.
* **nondeterminism fixes** — the client RNG seed plumbing
  (``MochiDBClient.rng_seed``) and the ExplorerLoop shuffle-barrier fix
  (asyncio's fd/pipe bookkeeping keeps FIFO order; shuffling it across a
  task wakeup corrupted socket connects — found by this engine, the
  first consumer driving real sockets on the explorer loop).
* **a smoke-scale soak** (~8 seeds; ``MOCHI_SCENARIO_SEEDS`` widens the
  slow-marked leg) with zero violations and zero harness errors.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os

import pytest

from mochi_tpu.testing import scenario
from mochi_tpu.testing.scenario import ScenarioSpec, draw_spec, run_scenario


def _spec(seed: int = 101, faults=(), **kw) -> ScenarioSpec:
    base = dict(
        seed=seed,
        profile="soak",
        backend="virtual",
        n_servers=4,
        rf=4,
        durable=False,
        net_seed=seed,
        rtt_ms=0.0,
        jitter_ms=0.0,
        drop=0.0,
        n_clients=1,
        keys_per_client=2,
        sweeps=1,
        value_bytes=16,
        timeout_s=2.0,
        op_attempts=6,
        faults=tuple(faults),
    )
    base.update(kw)
    return ScenarioSpec(**base)


# ---------------------------------------------------------------- determinism


def test_spec_draw_is_deterministic_and_json_roundtrips():
    for seed in (0, 3, 10, 41):
        a, b = draw_spec(seed), draw_spec(seed)
        assert a == b
        assert a.spec_hash() == b.spec_hash()
        rt = ScenarioSpec.from_json(a.to_json())
        assert rt == a and rt.spec_hash() == a.spec_hash()
    assert draw_spec(1).spec_hash() != draw_spec(2).spec_hash()


def test_same_seed_three_runs_byte_identical():
    records = [run_scenario(4).canonical_bytes() for _ in range(3)]
    assert records[0] == records[1] == records[2]
    doc = json.loads(records[0])
    assert doc["verdict"]["ok"] is True
    assert doc["acked"], "a run with no acked writes pins nothing"
    assert doc["schedule"][-1] == "final: invariants ok"


# ------------------------------------------------------- one leg per family


def test_family_crash_restart_durable_replays_wal():
    spec = _spec(
        201,
        durable=True,
        wal_fsync="off",
        faults=[{"family": "crash-restart", "victim": "server-1", "resync": True}],
    )
    res = run_scenario(spec)
    assert res.ok, (res.error, res.violations)
    assert any("restart server-1 convicted=0" in s for s in res.steps), res.steps
    replays = res.info.get("replays")
    assert replays and replays[0]["entries"] > 0  # recovery actually replayed
    assert res.report["storage_replay_convictions"] == 0


def test_family_partition_heal_drops_and_recovers():
    spec = _spec(
        202,
        faults=[{"family": "partition-heal", "victim": "server-2", "hold_s": 0.2}],
    )
    res = run_scenario(spec)
    assert res.ok, (res.error, res.violations)
    assert any("partition server-2" in s for s in res.steps)
    assert any("heal server-2" in s for s in res.steps)
    # the partition must have actually eaten frames, or the leg is a no-op
    assert res.info["netsim_totals"]["dropped"] > 0


def test_family_byzantine_replica_invariants_hold():
    spec = _spec(
        203,
        n_servers=5,
        faults=[{"family": "byz-replica", "sid": "server-1", "strategy": "equivocate"}],
    )
    res = run_scenario(spec)
    assert res.ok, (res.error, res.violations)
    assert res.report["byzantine_replicas"] == ["server-1"]
    assert res.report["honest_replicas"] == [
        f"server-{i}" for i in range(5) if i != 1
    ]


def test_family_byzantine_client_attacks_and_invariants_hold():
    spec = _spec(
        204,
        faults=[
            {
                "family": "byz-client",
                "strategy": "withhold",
                "seed": 9,
                "ttl_ms": 300.0,
                "quota": 64,
                "wedge_seeds": 32,
            }
        ],
    )
    res = run_scenario(spec)
    assert res.ok, (res.error, res.violations)
    stats = res.info["byz_client_stats"][0]
    assert stats["strategy"] == "withhold"
    assert stats["write1_sent"] > 0  # the adversary actually attacked


def test_family_load_spike_sheds_absorbed():
    spec = _spec(205, faults=[{"family": "load-spike", "burst": 8}])
    res = run_scenario(spec)
    assert res.ok, (res.error, res.violations)
    assert any("spike acked=8" in s for s in res.steps)
    assert len(res.acked) >= 8 + 2 * 2  # spike keys + warm/leg bursts


def test_family_reconfig_converges_under_writes():
    spec = _spec(206, faults=[{"family": "reconfig", "rounds": 1}])
    res = run_scenario(spec)
    assert res.ok, (res.error, res.violations)
    assert any("reconfig configstamp=2" in s for s in res.steps), res.steps


def test_family_sigkill_process_cluster_recovers_acked():
    spec = _spec(
        207,
        backend="process",
        durable=True,
        wal_fsync="group",
        keys_per_client=3,
        timeout_s=8.0,
        faults=[{"family": "sigkill", "victims": 1, "restart": True}],
    )
    res = run_scenario(spec)
    assert res.ok, (res.error, res.violations)
    assert any("sigkill server-0" in s for s in res.steps)
    assert res.report["backend"] == "process"
    assert res.report["acked_writes"] == len(res.acked) > 0


def test_family_sigkill_paged_engine_recovers_acked():
    """The round-17 paged engine under the harshest family: SIGKILL a real
    process mid-load, restart, recover from page index + WAL tail — every
    acked write must read back (the engine dimension of generator v2)."""
    spec = _spec(
        208,
        backend="process",
        durable=True,
        wal_fsync="group",
        engine="paged",
        keys_per_client=3,
        timeout_s=8.0,
        faults=[{"family": "sigkill", "victims": 1, "restart": True}],
    )
    res = run_scenario(spec)
    assert res.ok, (res.error, res.violations)
    assert any("sigkill server-0" in s for s in res.steps)
    assert any("engine=paged" in s for s in res.steps), res.steps
    assert res.report["acked_writes"] == len(res.acked) > 0


def test_engine_dimension_drawn_and_gated_on_durable():
    """Generator v2's engine stream: paged and wal both actually drawn,
    never a paged engine without durability, and the dimension rides a
    NEW stream (existing components' draws did not shift)."""
    engines = set()
    for seed in range(160):
        sp = draw_spec(seed)
        engines.add((sp.durable, sp.engine))
        if not sp.durable:
            assert sp.engine == "wal", seed
    assert (True, "paged") in engines
    assert (True, "wal") in engines


def test_fastpath_dimension_draws_both_postures():
    """Generator v3's fastpath stream: both verification postures actually
    drawn (the signed-everything wire keeps soak weight), riding a NEW
    stream so existing components' draws did not shift."""
    postures = {draw_spec(seed).fast_path for seed in range(32)}
    assert postures == {True, False}


def test_pinned_seed_fast_path_on_posture_lands_cluster_wide():
    """Round-18 posture pin, fast path ON (seed 4 draws fast_path=True —
    re-pin the seed if the draw ever shifts): the MAC'd-session wire runs
    a full scenario with invariants held, and the drawn posture actually
    landed on every replica and client."""
    spec = draw_spec(4)
    assert spec.fast_path is True, spec
    res = run_scenario(spec)
    assert res.ok, (res.error, res.violations)
    assert any("fast_path=True" in s for s in res.steps), res.steps
    assert res.info["fast_path_postures"] == {
        "spec": True, "replicas": [True], "clients": [True],
    }


def test_pinned_seed_fast_path_off_posture_lands_cluster_wide():
    """Round-18 posture pin, fast path OFF (seed 11 draws
    fast_path=False): the pre-r18 signed-everything wire stays a
    first-class soak posture — spec-pinned, immune to MOCHI_FAST_PATH."""
    spec = draw_spec(11)
    assert spec.fast_path is False, spec
    res = run_scenario(spec)
    assert res.ok, (res.error, res.violations)
    assert any("fast_path=False" in s for s in res.steps), res.steps
    assert res.info["fast_path_postures"] == {
        "spec": False, "replicas": [False], "clients": [False],
    }


# ------------------------------------------------------------- violation arc


def test_injected_violation_detect_dump_replay_minimize(tmp_path):
    flight = str(tmp_path / "flights")
    spec = dataclasses.replace(draw_spec(4), inject_violation=True)
    res = run_scenario(spec, flight_dir=flight)
    # detected
    assert not res.ok and res.violations
    assert "conflicting commits" in res.violations[0]
    # dumped, with the scenario seed stamped into the artifact
    dumps = res.info["flight_dumps"]
    assert dumps, "violation produced no flight dumps"
    with open(os.path.join(flight, dumps[0])) as fh:
        doc = json.load(fh)
    stamp = doc["run"]
    assert stamp["scenario_seed"] == 4
    assert stamp["injected"] is True
    # the dump's stamp regenerates the IDENTICAL spec (repro --seed / --dump)
    redrawn = dataclasses.replace(
        draw_spec(stamp["scenario_seed"], stamp["profile"]),
        inject_violation=stamp["injected"],
    )
    assert redrawn.spec_hash() == stamp["spec_hash"] == spec.spec_hash()
    # replays byte-identically from the seed alone
    again = run_scenario(redrawn)
    assert again.canonical_bytes() == res.canonical_bytes()
    # minimizes to a strictly smaller spec that still reproduces
    mini = scenario.minimize(spec)
    assert mini.spec.weight() < spec.weight()
    still = run_scenario(mini.spec)
    assert still.violations and "conflicting commits" in still.violations[0]
    repro = mini.reproducer()
    assert repro["spec_hash"] == mini.spec.spec_hash()


def test_report_carries_run_stamp():
    from mochi_tpu.obs import trace as obs_trace
    from mochi_tpu.testing.invariants import InvariantChecker

    try:
        obs_trace.set_run_stamp(scenario_seed=5, spec_hash="abcd")
        report = InvariantChecker([]).report()
        assert report["run"]["scenario_seed"] == 5
        assert report["run"]["spec_hash"] == "abcd"
    finally:
        obs_trace.clear_run_stamp()
    assert "run" not in InvariantChecker([]).report()


# ------------------------------------------------- nondeterminism regressions


def test_client_rng_seed_replays_draw_sequence():
    """The SDK's RNG (Write1 seed draws, backoff jitter) must ride the
    scenario seed: unseeded OS entropy here made two same-seed scenario
    runs diverge at the first seed collision/backoff (round-16 fix)."""
    from mochi_tpu.client.client import MochiDBClient
    from mochi_tpu.cluster.config import ClusterConfig
    from mochi_tpu.crypto.keys import generate_keypair

    kps = {f"server-{i}": generate_keypair() for i in range(4)}
    cfg = ClusterConfig.build(
        {sid: "127.0.0.1:1" for sid in kps},
        rf=4,
        public_keys={sid: kp.public_key for sid, kp in kps.items()},
    )

    async def draws(rng_seed):
        client = MochiDBClient(config=cfg, rng_seed=rng_seed)
        try:
            return [client._rand.randrange(1000) for _ in range(8)]
        finally:
            await client.close()

    async def case():
        a = await draws(7)
        b = await draws(7)
        c = await draws(8)
        assert a == b, "same rng_seed must replay the same draw sequence"
        assert a != c
    asyncio.run(case())


def test_explorer_loop_keeps_asyncio_bookkeeping_fifo():
    """Regression for the shuffle-vs-sock_connect race: the ExplorerLoop
    reordering ``_sock_write_done`` after the task wakeup that creates
    the connection's transport raised 'File descriptor N is used by
    transport ...' inside loop callbacks and left connect watchers
    registered.  Drive real socket connects on several seeds and assert
    the loop's exception handler stays silent."""
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.schedule import ExplorerLoop
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    for seed in range(4):
        loop = ExplorerLoop(seed)
        asyncio.set_event_loop(loop)
        errors = []
        loop.set_exception_handler(
            lambda l, ctx: errors.append(
                f"{ctx.get('message')}: {ctx.get('exception')!r}"
            )
        )

        async def case():
            async with VirtualCluster(4, rf=4) as vc:
                client = vc.client(timeout_s=5.0)
                await client.execute_write_transaction(
                    TransactionBuilder().write("fifo-pin", b"v").build()
                )

        try:
            loop.run_until_complete(case())
        finally:
            asyncio.set_event_loop(None)
            loop.close()
        fd_errors = [e for e in errors if "File descriptor" in e]
        assert not fd_errors, (seed, fd_errors)


def test_silent_byzantine_plus_reconfig_converges_honest_only():
    """Soak-found composition bug (seeds 164/195/275/319/425 of the
    round-16 bring-up, results_r16.json): the reconfig leg waited for
    EVERY replica to learn the new configstamp, but a silent adversary
    never answers the config-resync traffic that would teach it — every
    silent+reconfig draw wedged at the 15 s convergence deadline.
    Convergence is only promised for honest replicas."""
    spec = _spec(
        208,
        n_servers=5,
        faults=[
            {"family": "byz-replica", "sid": "server-1", "strategy": "silent"},
            {"family": "reconfig", "rounds": 1},
        ],
    )
    res = run_scenario(spec)
    assert res.ok, (res.error, res.violations)
    assert any("reconfig configstamp=2" in s for s in res.steps), res.steps


def test_final_check_retries_transient_read_failure():
    """Soak-found verdict bug (seed 64): ONE un-retried quorum read that
    timed out under host overload convicted 'acked write unreadable' —
    a tenancy artifact recorded as durability loss.  final_check now
    retries (the SDK's recovery machinery is part of the contract); a
    key that stays unreadable through the retries still convicts."""
    from mochi_tpu.testing.invariants import InvariantChecker

    class FlakyClient:
        def __init__(self, fail_times: int):
            self.fail_times = fail_times
            self.calls = 0

        async def execute_read_transaction(self, txn):
            self.calls += 1
            if self.calls <= self.fail_times:
                raise TimeoutError("stalled responders")

            class Op:
                value = b"v"
                existed = True

            class Res:
                operations = [Op()]

            return Res()

    async def case():
        checker = InvariantChecker([])
        checker.record_ack("k", b"v")
        flaky = FlakyClient(fail_times=1)
        await checker.final_check(flaky)
        assert checker.ok, checker.violations  # one transient → recovered
        assert flaky.calls == 2

        checker2 = InvariantChecker([])
        checker2.record_ack("k", b"v")
        dead = FlakyClient(fail_times=99)
        await checker2.final_check(dead)
        assert not checker2.ok  # persistent unreadability still convicts
        assert "unreadable" in checker2.violations[0]

    asyncio.run(case())


# ---------------------------------------------------------------------- soak


def test_soak_smoke_eight_seeds():
    summary = scenario.soak(range(8))
    assert summary["seeds_run"] == 8
    assert summary["violations"] == 0, summary["failing_seeds"]
    assert summary["harness_errors"] == 0, summary["failing_seeds"]
    assert summary["acked_writes"] > 0
    # at least a few distinct families drawn even at smoke scale
    drawn = [f for f, n in summary["fault_family_draws"].items() if n > 0]
    assert len(drawn) >= 3, summary["fault_family_draws"]


@pytest.mark.slow
def test_soak_slow_wide():
    count = scenario.soak_seed_count(64)
    summary = scenario.soak(range(count), workers=2)
    assert summary["seeds_run"] == count
    assert summary["violations"] == 0, summary["failing_seeds"]
    assert summary["harness_errors"] == 0, summary["failing_seeds"]
    assert all(
        summary["fault_family_draws"].get(f, 0) > 0 for f in scenario.FAMILIES
    ), summary["fault_family_draws"]

"""Model-based testing: the cluster vs a plain dict.

A single client issuing sequential transactions must observe exactly
dict semantics — the quorum protocol, grants, epochs, certificates and
sharding are all implementation detail below that contract (the
reference asserts this only for hand-picked sequences,
``MochiClientServerCommunicationTest.java``; here the sequences are
generated).  Multi-key transactions apply atomically; duplicate keys in
one transaction are last-write-wins (round-2 semantics decision,
matching the reference's sequential apply).
"""

from __future__ import annotations

import asyncio

import numpy as np

from mochi_tpu.client.txn import TransactionBuilder
from mochi_tpu.testing.virtual_cluster import VirtualCluster


def run(coro):
    asyncio.run(coro)


KEYS = [f"mb-{i}" for i in range(8)]


def test_random_op_sequences_match_dict_semantics():
    rng = np.random.default_rng(0xC0FFEE)

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            model: dict = {}
            for step in range(120):
                kind = rng.integers(0, 4)
                if kind == 0:  # single write
                    k = KEYS[rng.integers(len(KEYS))]
                    v = b"s%d" % step
                    await client.execute_write_transaction(
                        TransactionBuilder().write(k, v).build()
                    )
                    model[k] = v
                elif kind == 1:  # single delete
                    k = KEYS[rng.integers(len(KEYS))]
                    await client.execute_write_transaction(
                        TransactionBuilder().delete(k).build()
                    )
                    model.pop(k, None)
                elif kind == 2:  # multi-key txn, possibly duplicate keys
                    tb = TransactionBuilder()
                    picks = [
                        KEYS[rng.integers(len(KEYS))]
                        for _ in range(int(rng.integers(2, 5)))
                    ]
                    staged: dict = {}
                    for j, k in enumerate(picks):
                        if rng.integers(2):
                            v = b"m%d-%d" % (step, j)
                            tb.write(k, v)
                            staged[k] = v
                        else:
                            tb.delete(k)
                            staged[k] = None
                    await client.execute_write_transaction(tb.build())
                    for k, v in staged.items():
                        if v is None:
                            model.pop(k, None)
                        else:
                            model[k] = v
                else:  # read a random subset, check against the model
                    tb = TransactionBuilder()
                    picks = [
                        KEYS[rng.integers(len(KEYS))]
                        for _ in range(int(rng.integers(1, 4)))
                    ]
                    for k in picks:
                        tb.read(k)
                    res = await client.execute_read_transaction(tb.build())
                    for k, op in zip(picks, res.operations):
                        if k in model:
                            assert op.existed and op.value == model[k], (
                                step, k, op.value, model[k],
                            )
                        else:
                            assert not op.existed, (step, k)
            # final audit: every key
            tb = TransactionBuilder()
            for k in KEYS:
                tb.read(k)
            res = await client.execute_read_transaction(tb.build())
            for k, op in zip(KEYS, res.operations):
                if k in model:
                    assert op.existed and op.value == model[k], k
                else:
                    assert not op.existed, k
            await client.close()

    run(main())

"""Paged storage engine (round 17, ``mochi_tpu/storage/paged.py``): engine
selection through the SPI, restart -> page-index rebuild -> on-demand
fault-in under a cache cap far below the data set, per-entry tamper
conviction on self-certifying pages, incremental compaction, and the
cross-process SIGKILL -> restart -> zero-acked-write-loss contract on the
paged engine.

The tamper tests mirror the WAL Byzantine-restart story one layer down: an
adversary who rewrites a page recomputes every CRC and the footer's
transaction hash trivially, so framing is NOT the integrity argument — the
per-entry recheck pins the entry's grants to the transaction they actually
signed, and grant signatures re-verify in batch at audit/compaction (the
DSig posture).  Each tampered entry is convicted with key attribution and
never served; the honest value still answers from the replica quorum.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import shutil
import tempfile
import zlib

from mochi_tpu.client.txn import TransactionBuilder
from mochi_tpu.protocol import Transaction, transaction_hash
from mochi_tpu.protocol.codec import encode
from mochi_tpu.storage import PagedStorage
from mochi_tpu.storage.durable import DurableStorage
from mochi_tpu.storage.paged import (
    _write_page,
    page_name,
    read_page_entry,
    scan_page_footer,
)
from mochi_tpu.storage.spi import build_storage
from mochi_tpu.testing.invariants import InvariantChecker
from mochi_tpu.testing.process_cluster import ProcessCluster
from mochi_tpu.testing.virtual_cluster import VirtualCluster


@contextlib.contextmanager
def _paged_env(cache_bytes: int = 2048, memtable_bytes: int = 4096):
    """Pin tiny caps for the duration of a test (the engine reads them at
    construction, i.e. at every boot/restart inside the block)."""
    keys = {
        "MOCHI_PAGE_CACHE_BYTES": str(cache_bytes),
        "MOCHI_MEMTABLE_BYTES": str(memtable_bytes),
    }
    saved = {k: os.environ.get(k) for k in keys}
    os.environ.update(keys)
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


async def _populated(td: str, n: int = 12):
    vc = VirtualCluster(4, rf=4, storage_dir=td, storage_engine="paged")
    await vc.start()
    client = vc.client()
    for i in range(n):
        await client.execute_write_transaction(
            TransactionBuilder().write(f"pk{i}", b"v%d" % i).build()
        )
    return vc, client


async def _flush_to_pages(replica) -> None:
    """Force the memtable out: every committed key lands in a page and the
    WAL truncates behind the manifest watermark."""
    await replica.storage.flush()
    await replica.storage.snapshot(replica.store)


def _freeze_storage(td: str, server_id: str) -> str:
    src = os.path.join(td, server_id)
    dst = src + ".crash"
    shutil.copytree(src, dst)
    return dst


def _restore_storage(td: str, server_id: str, frozen: str) -> None:
    dst = os.path.join(td, server_id)
    shutil.rmtree(dst)
    shutil.move(frozen, dst)


def _rewrite_page_with(directory: str, server_id: str, mutate) -> str:
    """Adversarial page rewrite: pick a page holding a data key, decode its
    entries, apply ``mutate(key, entry_obj) -> bool`` to each decoded
    ``[key, txn_obj, cert_obj, epoch]`` until one reports mutation, then
    rewrite the page with every CRC and the footer transaction hash
    RECOMPUTED (an adversary recomputes them trivially).  Returns the
    mutated key."""
    tampered = None
    for name in sorted(os.listdir(directory)):
        if not name.startswith("page-") or not name.endswith(".pg"):
            continue
        path = os.path.join(directory, name)
        page_id, rows, _size = scan_page_footer(path, server_id)
        entries = []
        for key, off, length, crc, _txh, epoch in rows:
            obj = read_page_entry(path, off, length, crc)
            if tampered is None and mutate(key, obj):
                tampered = key
            blob = encode(obj)
            txh = transaction_hash(Transaction.from_obj(obj[1]))
            entries.append((key, blob, zlib.crc32(blob), txh, int(epoch)))
        if tampered is not None:
            _write_page(path, server_id, page_id, entries)
            return tampered
    raise AssertionError("no data page found to tamper with")


# ------------------------------------------------------- engine selection


def test_engine_selection_param_env_and_rejection(tmp_path):
    s = build_storage(str(tmp_path / "a"), "server-0")
    assert isinstance(s, DurableStorage) and not isinstance(s, PagedStorage)
    assert s.name == "durable" and s.pager is False

    p = build_storage(str(tmp_path / "b"), "server-0", engine="paged")
    assert isinstance(p, PagedStorage)
    assert p.name == "paged" and p.pager is True

    saved = os.environ.get("MOCHI_STORAGE_ENGINE")
    os.environ["MOCHI_STORAGE_ENGINE"] = "paged"
    try:
        q = build_storage(str(tmp_path / "c"), "server-0")
        assert isinstance(q, PagedStorage)
        # an explicit param beats the environment
        w = build_storage(str(tmp_path / "d"), "server-0", engine="wal")
        assert not isinstance(w, PagedStorage)
    finally:
        if saved is None:
            os.environ.pop("MOCHI_STORAGE_ENGINE", None)
        else:
            os.environ["MOCHI_STORAGE_ENGINE"] = saved

    try:
        build_storage(str(tmp_path / "e"), "server-0", engine="lsm9000")
    except ValueError as exc:
        assert "lsm9000" in str(exc)
    else:
        raise AssertionError("unknown engine accepted silently")


# ------------------------------------- restart -> fault-in under a tiny cap


def test_paged_recover_faults_in_under_tiny_cache():
    """Restart from pages with a cache cap far below the value bytes: the
    boot rebuilds only the index (no values), every read faults its page
    entry in through the verified sink, the CLOCK keeps residency at the
    cap, and nothing is convicted."""

    async def body(td):
        vc, _client = await _populated(td, n=24)
        try:
            victim = vc.replica("server-1")
            await _flush_to_pages(victim)
            fresh = await vc.restart_replica("server-1")
            report = fresh.storage.replay_report()
            assert report["convicted"] == 0, report
            st = fresh.storage.stats()
            assert st["pages"]["count"] >= 1, st
            for i in range(24):
                sv = fresh.store._get(f"pk{i}")
                assert sv is not None and sv.value == b"v%d" % i, f"pk{i}"
            st = fresh.storage.stats()
            assert st["cache"]["misses"] >= 24, st
            # the cap bounds residency: 24 values cannot all stay resident
            assert st["cache"]["evictions"] > 0, st
            assert st["pages"]["convicted"] == 0, st
            checker = InvariantChecker([fresh])
            checker.check_now()
            rep = checker.report()
            assert rep["ok"], rep["violations"]
        finally:
            await vc.close()

    with _paged_env(cache_bytes=512, memtable_bytes=2048):
        with tempfile.TemporaryDirectory() as td:
            asyncio.run(asyncio.wait_for(body(td), timeout=120))


# --------------------------------------------------- Byzantine page tamper


def test_tampered_page_value_convicted_and_quorum_serves_honest():
    """The round-17 pin: one page entry's committed value mutated on disk
    with ALL integrity frames recomputed (entry CRC, footer row, footer
    transaction hash).  Framing accepts the page at boot — but the entry's
    grants signed the ORIGINAL transaction hash, so the first fault-in (or
    the boot audit, whichever wins the race) refuses it, convicts with
    per-entry attribution, and the tampered value is never served.  The
    honest value still answers from the replica quorum."""

    async def body(td):
        vc, client = await _populated(td)
        try:
            victim = vc.replica("server-1")
            await _flush_to_pages(victim)
            frozen = _freeze_storage(td, "server-1")

            def mutate(key, obj) -> bool:
                if not key.startswith("pk"):
                    return False
                for op in obj[1]:  # txn obj: op list; op: [action, key, value]
                    if op[1] == key and op[2] is not None:
                        op[2] = b"EVIL"
                        return True
                return False

            tampered = _rewrite_page_with(frozen, "server-1", mutate)

            fresh = await vc.restart_replica(
                "server-1",
                before_boot=lambda sid: _restore_storage(td, sid, frozen),
            )
            # first touch faults the tampered entry in -> per-entry recheck
            sv = fresh.store._get(tampered)
            assert sv is None or sv.value != b"EVIL", sv
            report = fresh.storage.replay_report()
            assert report["convicted"] >= 1, report
            assert any(
                c["key"] == tampered for c in report["convictions"]
            ), report
            assert any(
                "rejected" in c["reason"] for c in report["convictions"]
            ), report
            st = fresh.storage.stats()
            assert st["pages"]["convicted"] >= 1, st
            # invariant 5 surfaces the conviction as evidence, not violation
            checker = InvariantChecker([fresh])
            checker.check_now()
            rep = checker.report()
            assert rep["storage_replay_convictions"] >= 1, rep
            assert rep["ok"], rep["violations"]
            # the three honest replicas still answer with the real value
            idx = int(tampered[len("pk"):])
            res = await client.execute_read_transaction(
                TransactionBuilder().read(tampered).build()
            )
            assert res.operations[0].value == b"v%d" % idx
        finally:
            await vc.close()

    with _paged_env():
        with tempfile.TemporaryDirectory() as td:
            asyncio.run(asyncio.wait_for(body(td), timeout=120))


def test_forged_grant_signature_in_page_convicted_by_audit():
    """DSig posture, adversarial leg: a page entry's grant signatures
    zeroed (transaction untouched, so every hash agreement PASSES — the
    fault-in recheck alone cannot see this).  The batch signature sweep
    (boot audit) is exactly the layer that must catch it."""

    async def body(td):
        vc, _client = await _populated(td)
        try:
            victim = vc.replica("server-1")
            await _flush_to_pages(victim)
            frozen = _freeze_storage(td, "server-1")

            def mutate(key, obj) -> bool:
                if not key.startswith("pk"):
                    return False
                for mg_obj in obj[2].values():  # cert obj: {sid: mg_obj}
                    mg_obj[3] = b"\x00" * 64  # MultiGrant signature slot
                return True

            tampered = _rewrite_page_with(frozen, "server-1", mutate)

            fresh = await vc.restart_replica(
                "server-1",
                before_boot=lambda sid: _restore_storage(td, sid, frozen),
            )
            audit = await fresh.storage.audit(fresh.store)
            assert audit["convicted"] >= 1, audit
            report = fresh.storage.replay_report()
            assert any(
                c["key"] == tampered and "signature" in c["reason"]
                for c in report["convictions"]
            ), report
            sv = fresh.store._get(tampered)
            assert sv is None or sv.grants == {}, sv
        finally:
            await vc.close()

    with _paged_env():
        with tempfile.TemporaryDirectory() as td:
            asyncio.run(asyncio.wait_for(body(td), timeout=120))


# ------------------------------------------------------------- compaction


def test_compaction_drops_superseded_and_reverifies():
    """Two generations of the same keys -> two pages, the older one fully
    dead.  Incremental compaction merges the victims into one page, drops
    the superseded versions, re-verifies every surviving entry's grant
    signatures, and every value still reads back."""

    async def body(td):
        vc, client = await _populated(td, n=10)
        try:
            victim = vc.replica("server-1")
            await _flush_to_pages(victim)
            for i in range(10):
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"pk{i}", b"w%d" % i).build()
                )
            await _flush_to_pages(victim)
            st0 = victim.storage.stats()
            assert st0["pages"]["count"] >= 2, st0
            assert st0["compaction"]["debt"] > 0, st0

            done = await victim.storage.compact()
            assert done["rewritten"] >= 1, done
            st1 = victim.storage.stats()
            assert st1["pages"]["count"] < st0["pages"]["count"], (st0, st1)
            assert st1["compaction"]["runs"] >= 1, st1
            assert st1["compaction"]["reverified"] >= 10, st1
            assert st1["compaction"]["debt"] == 0, st1
            assert st1["pages"]["convicted"] == 0, st1

            # restart on the compacted image: everything replays clean
            fresh = await vc.restart_replica("server-1")
            assert fresh.storage.replay_report()["convicted"] == 0
            for i in range(10):
                sv = fresh.store._get(f"pk{i}")
                assert sv is not None and sv.value == b"w%d" % i, f"pk{i}"
        finally:
            await vc.close()

    with _paged_env():
        with tempfile.TemporaryDirectory() as td:
            asyncio.run(asyncio.wait_for(body(td), timeout=120))


# --------------------------------------- cross-process SIGKILL -> recover


def test_paged_sigkill_full_cluster_zero_acked_write_loss():
    """The acceptance pin on the paged engine: ProcessCluster under live
    load, EVERY replica SIGKILLed mid-stream, all four restarted from
    pages + WAL tail, and every acknowledged write must read back."""

    async def body():
        async with ProcessCluster(
            4,
            rf=4,
            n_processes=4,
            storage_dir=True,
            wal_fsync="group",
            storage_engine="paged",
        ) as pc:
            client = pc.client(timeout_s=8.0)
            acked = {}

            async def load():
                i = 0
                while True:
                    key, value = f"gk{i}", b"v%d" % i
                    try:
                        await client.execute_write_transaction(
                            TransactionBuilder().write(key, value).build()
                        )
                    except Exception:
                        return  # in-flight at the kill: indeterminate
                    acked[key] = value
                    i += 1

            writer = asyncio.ensure_future(load())
            while len(acked) < 10:
                await asyncio.sleep(0.02)
            for i in range(4):
                pc.kill_replica(f"server-{i}")
            await writer
            await client.close()

            for i in range(4):
                await pc.restart_replica(f"server-{i}")
            reader = pc.client(timeout_s=8.0)
            lost = []
            for key, value in sorted(acked.items()):
                res = await reader.execute_read_transaction(
                    TransactionBuilder().read(key).build()
                )
                if res.operations[0].value != value:
                    lost.append(key)
            assert not lost, f"{len(lost)} acked writes lost: {lost[:5]}"
            pc.check_alive()

    asyncio.run(asyncio.wait_for(body(), timeout=240))

"""Multi-host DCN path proof: 2 OS processes, one global 8-device mesh.

Runs ``mochi_tpu.parallel.multihost._demo_main`` in two subprocesses —
process 0 hosts the ``jax.distributed`` coordinator — each with 4 virtual
CPU devices (``--xla_force_host_platform_device_count``), and asserts:

* both processes join one runtime (process_count == 2, 8 global devices);
* the sharded verify + quorum ``psum`` runs across the process boundary;
* both processes compute identical, closed-form-correct group tallies.

This is the documented single-machine recipe for exercising the real
multi-host code path (the same calls a TPU pod slice runs under); the
reference has no distributed runtime to compare against (SURVEY.md §2.9).

NOTE: this test needs jaxlib multiprocess collectives and SKIPS on images
whose CPU backend rejects them (the guarded skip below).  The repo's own
multi-process deployment surface is covered WITHOUT that dependency by
``tests/test_process_cluster.py`` (ProcessCluster: real server processes,
cross-shard transactions, f=1 crash faults, graceful drain) — that suite
runs on bare CI images and never skips.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Some jaxlib builds (e.g. 0.4.36 on this image) reject cross-process
# collectives outright on the host platform with exactly this error — the
# single-machine recipe below then CANNOT run, on any amount of fixing on
# our side.  Skip with the runtime's own words; anything else is a real
# failure and still fails.
_CPU_MULTIPROC_UNSUPPORTED = (
    "Multiprocess computations aren't implemented on the CPU backend"
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_mesh_quorum_step():
    port = _free_port()
    lanes = 8  # per process; lanes i%4==3 corrupted, group = i%3
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    # append (not prepend): with repeated flags XLA honors the LAST one, and
    # the test harness environment may already force a device count
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                "-m",
                "mochi_tpu.parallel.multihost",
                "--coordinator",
                f"127.0.0.1:{port}",
                "--num-processes",
                "2",
                "--process-id",
                str(pid),
                "--lanes-per-process",
                str(lanes),
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    results = [p.communicate(timeout=240) for p in procs]
    if any(
        p.returncode != 0 and _CPU_MULTIPROC_UNSUPPORTED in err
        for p, (_, err) in zip(procs, results)
    ):
        pytest.skip(
            "this jaxlib's CPU backend cannot run multiprocess computations "
            f"({_CPU_MULTIPROC_UNSUPPORTED!r}); the multi-host path needs a "
            "real multi-device backend here"
        )
    for p, (out, err) in zip(procs, results):
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))

    # Closed-form expectation: per process, lanes 0..7 -> groups
    # [0,1,2,0,1,2,0,1], corrupted lanes {3,7} -> groups {0,1}.  Valid per
    # process: g0 gets lanes {0,6}=2, g1 gets {1,4}... compute directly:
    valid_per_group = [0, 0, 0]
    for i in range(lanes):
        if i % 4 != 3:
            valid_per_group[i % 3] += 1
    expected = [2 * v for v in valid_per_group]  # two identical processes

    for rec in outs:
        assert rec["process_count"] == 2
        assert rec["global_devices"] == 8
        assert rec["local_devices"] == 4
        assert rec["counts"] == expected, rec
        assert rec["committed"] == [c >= 3 for c in expected]
        assert rec["local_valid"] == sum(valid_per_group)
        # comb leg (registered-signer fast path) ran across the process
        # boundary with the identical-by-construction replicated table;
        # per-lane verdict pattern asserted inside the worker, the count
        # cross-checked here
        assert rec["comb_local_valid"] == sum(valid_per_group)
    # identical replicated tallies on both hosts
    assert outs[0]["counts"] == outs[1]["counts"]

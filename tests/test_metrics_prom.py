"""Round-15 exposition-hygiene satellites: a real parser roundtrips every
``mochi_*`` family (# HELP/# TYPE present, label values escape-safe even
for attacker-influenced peer/client ids), and per-identity label
cardinality is bounded with an ``other`` overflow series."""

from __future__ import annotations

import re

import pytest

from mochi_tpu.admin.http import (
    PROM_MAX_SERIES,
    _byzantine_prom,
    _cap_identities,
    _clients_prom,
    _fanout_prom,
    _num_activity,
)
from mochi_tpu.utils.metrics import Metrics, STRAGGLER_BOUNDS_MS

# ------------------------------------------------------------- the parser
#
# A faithful subset of the Prometheus text exposition format: enough to
# parse every line this repo emits and to UNESCAPE label values, so the
# roundtrip assertion is against parser-visible content, not substrings.

_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)\{(.*)\}\s+(\S+)$")


def _unescape(v: str) -> str:
    out = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            else:
                raise ValueError(f"bad escape \\{nxt}")
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(raw: str) -> dict:
    labels = {}
    i = 0
    while i < len(raw):
        eq = raw.index("=", i)
        name = raw[i:eq]
        assert raw[eq + 1] == '"', raw
        j = eq + 2
        buf = []
        while raw[j] != '"':
            if raw[j] == "\\":
                buf.append(raw[j : j + 2])
                j += 2
            else:
                buf.append(raw[j])
                j += 1
        labels[name] = _unescape("".join(buf))
        i = j + 1
        if i < len(raw) and raw[i] == ",":
            i += 1
    return labels


def parse_exposition(body: str):
    """-> (samples, helped, typed): every sample line parsed, and the
    family names that carried # HELP / # TYPE headers."""
    samples = []
    helped, typed = set(), set()
    for line in body.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            helped.add(line.split()[2])
            continue
        if line.startswith("# TYPE "):
            typed.add(line.split()[2])
            continue
        assert not line.startswith("#"), f"unknown comment line: {line}"
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name, raw_labels, value = m.groups()
        float(value)  # must be numeric
        samples.append((name, _parse_labels(raw_labels), float(value)))
    return samples, helped, typed


def _family(name: str) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix) and name != "mochi_timer_count":
            return name[: -len(suffix)]
    return name


# A deliberately hostile identity: quote, backslash, newline, and brace —
# everything that breaks naive exposition emitters.
EVIL_ID = 'peer"x\\y\nz{a="b"}'


def test_registry_exposition_roundtrips_every_family():
    m = Metrics()
    with m.timer("write-transactions"):
        pass
    m.mark("replica.write1-shed", 3)
    m.mark(f"suspect.bad-grant.{EVIL_ID}", 2)  # attacker-named counter
    m.set_gauge("overload.load", 0.25)
    m.histogram("replica.batch-occupancy").observe(4)
    body = m.to_prometheus({"server": EVIL_ID})
    samples, helped, typed = parse_exposition(body)
    assert samples
    families = {_family(name) for name, _, _ in samples}
    assert families <= helped, f"missing HELP: {families - helped}"
    assert families <= typed, f"missing TYPE: {families - typed}"
    # the hostile strings roundtrip exactly through escape + parse
    assert any(lab.get("server") == EVIL_ID for _, lab, _ in samples)
    assert any(
        lab.get("name") == f"suspect.bad-grant.{EVIL_ID}"
        for _, lab, _ in samples
    )
    by = {
        (name, lab.get("name", "")): v for name, lab, v in samples
    }
    assert by[("mochi_counter_total", f"suspect.bad-grant.{EVIL_ID}")] == 2


def test_fanout_family_escapes_and_caps_identities():
    m = Metrics()
    m.mark("fanout.early-return", 5)
    # one hostile peer + a Sybil flood far past the cap
    m.mark(f"fanout.late-response.{EVIL_ID}", 99)
    m.histogram(f"fanout-straggler-ms.{EVIL_ID}", STRAGGLER_BOUNDS_MS).observe(2.0)
    for i in range(PROM_MAX_SERIES * 4):
        m.mark(f"fanout.straggler-timeout.sybil-{i:04d}")
    body = _fanout_prom(m, "server", "server-0")
    samples, helped, typed = parse_exposition(body)
    assert "mochi_fanout" in helped and "mochi_fanout" in typed
    peers = {lab["peer"] for _, lab, _ in samples}
    # bounded: at most the cap (+1 for the aggregate peer="" row)
    assert len(peers - {""}) <= PROM_MAX_SERIES
    assert "other" in peers, "overflow identities must fold into 'other'"
    # the hostile high-activity peer keeps its own (escaped) row
    assert EVIL_ID in peers
    # the overflow row carries the folded counts (flood minus kept rows)
    other_total = sum(
        v for _, lab, v in samples
        if lab["peer"] == "other" and lab["stat"] == "straggler_timeout"
    )
    kept_sybils = sum(1 for p in peers if p.startswith("sybil-"))
    assert other_total == PROM_MAX_SERIES * 4 - kept_sybils


def test_byzantine_and_client_families_cap_identities():
    class _StubReplica:
        server_id = "server-0"

        def byzantine_stats(self):
            return {
                "equivocations": {
                    f"sybil-{i:04d}": 1 for i in range(PROM_MAX_SERIES * 2)
                },
                "bad_grants": {EVIL_ID: 7},
                "resync_bad_certificates": 1,
            }

        def client_grant_stats(self):
            return {
                "quota": 64,
                "ttl_ms": 1000,
                "reclaims": 0,
                "quota_refused": 0,
                "outstanding_total": 0,
                "max_wedge_ms": 0.0,
                "open_wedges": 0,
                "quota_refusals_served": 0,
                "banned_clients": 0,
                "per_client": {
                    f"client-{i:05d}": {"issued": i, "outstanding": 1}
                    for i in range(PROM_MAX_SERIES * 3)
                },
            }

    r = _StubReplica()
    samples, helped, typed = parse_exposition(_byzantine_prom(r))
    assert "mochi_byzantine" in helped and "mochi_byzantine" in typed
    eq_peers = {
        lab["peer"] for _, lab, _ in samples if lab["stat"] == "equivocations"
    }
    assert len(eq_peers) <= PROM_MAX_SERIES and "other" in eq_peers
    assert any(lab["peer"] == EVIL_ID for _, lab, _ in samples)

    samples, helped, typed = parse_exposition(_clients_prom(r))
    assert "mochi_client" in helped and "mochi_client" in typed
    clients = {lab["client"] for _, lab, _ in samples} - {""}
    assert len(clients) <= PROM_MAX_SERIES and "other" in clients
    # highest-activity identities keep their rows; the long tail folds
    assert f"client-{PROM_MAX_SERIES * 3 - 1:05d}" in clients
    other_issued = sum(
        v for _, lab, v in samples
        if lab["client"] == "other" and lab["stat"] == "issued"
    )
    assert other_issued > 0


def test_cap_identities_keeps_top_activity_and_merges_other():
    table = {f"id-{i:03d}": {"n": i} for i in range(PROM_MAX_SERIES + 40)}
    capped = _cap_identities(table, _num_activity)
    assert len(capped) == PROM_MAX_SERIES
    assert "other" in capped
    # the top-activity identity survives; the least-active folded
    assert f"id-{PROM_MAX_SERIES + 39:03d}" in capped
    assert "id-000" not in capped
    folded = set(table) - set(capped)
    assert capped["other"]["n"] == sum(int(k[3:]) for k in folded)
    # under the cap: untouched (no 'other' row manufactured)
    small = {"a": {"n": 1}, "b": {"n": 2}}
    assert _cap_identities(small, _num_activity) == small


@pytest.mark.parametrize("n_scrapes", [1, 3])
def test_full_admin_exposition_parses(n_scrapes):
    """End-to-end: a live replica's whole /metrics.prom body parses and
    every mochi_* family carries HELP + TYPE."""
    import asyncio

    from mochi_tpu.admin import AdminServer
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("pm-k", b"v").build()
            )
            admin = AdminServer(vc.replicas[0], port=0)
            await admin.start()
            try:
                import urllib.request

                loop = asyncio.get_running_loop()

                def _get():
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{admin.bound_port}/metrics.prom",
                        timeout=5,
                    ) as resp:
                        return resp.read().decode()

                for _ in range(n_scrapes):
                    body = await loop.run_in_executor(None, _get)
                    samples, helped, typed = parse_exposition(body)
                    families = {_family(name) for name, _, _ in samples}
                    assert families, "exposition must carry samples"
                    assert families <= helped, families - helped
                    assert families <= typed, families - typed
            finally:
                await admin.close()

    asyncio.run(asyncio.wait_for(main(), timeout=60))

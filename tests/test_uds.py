"""Unix-domain-socket transport: full cluster protocol over AF_UNIX.

A deployment option for single-host clusters (``gen_cluster --uds``,
``VirtualCluster(uds_dir=...)``, ``MOCHI_UDS=1``): same framed protocol,
no TCP/IP stack.  Measured on the 1-core CI host (config1 A/B, r4): no
throughput win over loopback TCP in either posture — the binding cost
there is scheduling/protocol work, not the network stack — so TCP stays
the default; the feature exists for multi-core single-host deployments
where the loopback send path is the demonstrated hot spot (BASELINE.md).
"""

from __future__ import annotations

import asyncio
import tempfile

from mochi_tpu.client.txn import TransactionBuilder
from mochi_tpu.cluster.config import ServerInfo
from mochi_tpu.testing.virtual_cluster import VirtualCluster


def test_server_info_unix_url_roundtrip():
    info = ServerInfo.from_url("server-0", "unix:/tmp/mochi-x/server-0.sock:0")
    assert info.is_unix and info.unix_path == "/tmp/mochi-x/server-0.sock"
    assert info.port == 0
    tcp = ServerInfo.from_url("server-1", "10.0.0.7:8101")
    assert not tcp.is_unix and tcp.host == "10.0.0.7" and tcp.port == 8101


def test_uds_double_bind_refused_stale_socket_reclaimed():
    """A second server must NOT steal a live server's socket (the TCP
    analog fails with EADDRINUSE); a stale socket from a dead process IS
    reclaimed at bind."""
    from mochi_tpu.net.transport import RpcServer

    async def body():
        with tempfile.TemporaryDirectory(prefix="mochi-uds-") as d:
            path = f"{d}/s.sock"

            async def handler(env):
                return None

            live = RpcServer(f"unix:{path}", 0, handler)
            await live.start()
            try:
                thief = RpcServer(f"unix:{path}", 0, handler)
                try:
                    await thief.start()
                    raise AssertionError("second bind on a live socket succeeded")
                except OSError:
                    pass
            finally:
                await live.close()
            import os

            assert not os.path.exists(path)  # close unlinked our socket
            # stale socket (no listener): simulate a dead process's leftover
            import socket as s

            sock = s.socket(s.AF_UNIX)
            sock.bind(path)
            sock.close()  # bound but never listening -> connect refused
            fresh = RpcServer(f"unix:{path}", 0, handler)
            await fresh.start()  # reclaims the stale path
            await fresh.close()

    asyncio.run(asyncio.wait_for(body(), timeout=30))


def test_cluster_over_uds():
    async def body():
        with tempfile.TemporaryDirectory(prefix="mochi-uds-") as d:
            async with VirtualCluster(5, rf=4, uds_dir=d) as vc:
                assert all(s.is_unix for s in vc.config.servers.values())
                c = vc.client()
                await c.execute_write_transaction(
                    TransactionBuilder().write("uk", "uv").build()
                )
                r = await c.execute_read_transaction(
                    TransactionBuilder().read("uk").build()
                )
                assert r.operations[0].value == b"uv"
                cert = r.operations[0].current_certificate
                assert cert is not None and len(cert.grants) >= vc.config.quorum

    asyncio.run(asyncio.wait_for(body(), timeout=60))

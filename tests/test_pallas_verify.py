"""Pallas verify kernel: differential parity with the XLA and CPU paths.

Runs in interpret mode on CPU (exact, slow) — small blocks/batches only.
The same kernel compiles for real on TPU (tiling: limbs on sublanes, batch
on 128-wide lanes).  Round 2: the kernel shares ``curve.verify_core`` with
the XLA path, so the only kernel-specific behavior left to test is the
``pallas_call`` plumbing (block specs, padding, transposes) and the
Mosaic-safe "shift" column accumulation.
"""

import numpy as np
import pytest

from mochi_tpu.crypto import batch_verify, keys
from mochi_tpu.crypto import pallas_verify as PV
from mochi_tpu.verifier.spi import VerifyItem


def _prep(items):
    return batch_verify.prepare(items)[:6]


@pytest.mark.slow
def test_pallas_kernel_matches_xla_path():
    """Full kernel through pl.pallas_call in interpret mode; on a TPU
    backend the same call compiles the real kernel via Mosaic."""
    kp = keys.generate_keypair()
    items = []
    for i in range(6):
        msg = b"pallas %d" % i
        sig = bytearray(kp.sign(msg))
        if i in (2, 4):
            sig[1] ^= 0x40  # forge
        items.append(VerifyItem(kp.public_key, msg, bytes(sig)))
    tensors = _prep(items)
    got = np.asarray(PV.verify_prepared_pallas(*tensors, block=8))
    expect = np.array([True, True, False, True, False, True])
    assert (got == expect).all()

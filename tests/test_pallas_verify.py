"""Pallas verify kernel: differential parity with the XLA and CPU paths.

Runs in interpret mode on CPU (exact, slow) — small blocks/batches only.
The same kernel compiles for real on TPU (tiling: limbs on sublanes, batch
on 128-wide lanes).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mochi_tpu.crypto import batch_verify, keys
from mochi_tpu.crypto import pallas_verify as PV
from mochi_tpu.crypto import field as F
from mochi_tpu.verifier.spi import VerifyItem


def _prep(items):
    return batch_verify.prepare(items)[:6]


def test_ll_field_ops_match_reference():
    rng = np.random.default_rng(7)
    ints = [0, 1, F.P_INT - 1, F.P_INT - 19, (1 << 255) - 20, (1 << 256) - 1]
    # random full-range values via python ints
    ints += [int.from_bytes(rng.bytes(32), "little") % (1 << 256) for _ in range(6)]
    a_ll = jnp.stack([jnp.asarray(F.int_to_limbs(v % (1 << 256))) for v in ints], axis=1)
    b_ll = jnp.stack(
        [jnp.asarray(F.int_to_limbs((v * 7 + 3) % (1 << 256))) for v in ints], axis=1
    )
    got_mul = PV.canonical_ll(PV.mul_ll(a_ll, b_ll))
    got_add = PV.canonical_ll(PV.add_ll(a_ll, b_ll))
    got_sub = PV.canonical_ll(PV.sub_ll(a_ll, b_ll))
    for i, v in enumerate(ints):
        a_int = v % (1 << 256)
        b_int = (v * 7 + 3) % (1 << 256)
        assert F.limbs_to_int(np.asarray(got_mul[:, i])) == (a_int * b_int) % F.P_INT
        assert F.limbs_to_int(np.asarray(got_add[:, i])) == (a_int + b_int) % F.P_INT
        assert F.limbs_to_int(np.asarray(got_sub[:, i])) == (a_int - b_int) % F.P_INT


@pytest.mark.slow
def test_pallas_kernel_matches_xla_path():
    """Full kernel through pl.pallas_call in interpret mode (~2 min on CPU;
    on a TPU backend the same call compiles the real kernel)."""
    kp = keys.generate_keypair()
    items = []
    for i in range(6):
        msg = b"pallas %d" % i
        sig = bytearray(kp.sign(msg))
        if i in (2, 4):
            sig[1] ^= 0x40  # forge
        items.append(VerifyItem(kp.public_key, msg, bytes(sig)))
    tensors = _prep(items)
    got = np.asarray(PV.verify_prepared_pallas(*tensors, block=8))
    expect = np.array([True, True, False, True, False, True])
    assert (got == expect).all()

"""Verifier RPC service: the shared-TPU sidecar boundary (VERDICT r1 #5).

A real multi-process cluster has one TPU owner; these tests prove the
service + RemoteVerifier pair end to end — in-process for speed (the
transport is the same real asyncio TCP the cluster uses), and via a full
``VirtualCluster`` whose replicas all route certificate checks through one
shared service.
"""

import asyncio

import pytest

from mochi_tpu.client import TransactionBuilder
from mochi_tpu.crypto.keys import generate_keypair
from mochi_tpu.testing import VirtualCluster
from mochi_tpu.verifier.service import RemoteVerifier, VerifierService
from mochi_tpu.verifier.spi import CpuVerifier, VerifyItem


def run(coro):
    asyncio.run(asyncio.wait_for(coro, timeout=120))


def make_items(n, forge=()):
    kp = generate_keypair()
    items = []
    for i in range(n):
        msg = b"svc message %d" % i
        sig = kp.sign(msg)
        if i in forge:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        items.append(VerifyItem(kp.public_key, msg, sig))
    return items


def test_remote_verify_mixed_batch():
    async def main():
        service = VerifierService(port=0, verifier=CpuVerifier())
        await service.start()
        rv = RemoteVerifier("127.0.0.1", service.bound_port)
        try:
            bitmap = await rv.verify_batch(make_items(8, forge={2, 5}))
            assert bitmap == [True, True, False, True, True, False, True, True]
            assert rv.remote_batches == 1 and rv.fallback_batches == 0
            assert service.requests == 1 and service.items == 8
        finally:
            await rv.close()
            await service.close()

    run(main())


def test_remote_verifier_falls_back_when_service_down():
    async def main():
        # nothing listening on this port
        rv = RemoteVerifier("127.0.0.1", 1, timeout_s=2.0)
        try:
            bitmap = await rv.verify_batch(make_items(4, forge={1}))
            # fallback still verifies (never skips): forged item rejected
            assert bitmap == [True, False, True, True]
            assert rv.fallback_batches == 1
        finally:
            await rv.close()

    run(main())


def test_shared_secret_authenticates_both_directions():
    async def main():
        secret = bytes(range(32))
        service = VerifierService(port=0, verifier=CpuVerifier(), secret=secret)
        await service.start()
        try:
            # matching secret: verdicts flow
            rv = RemoteVerifier("127.0.0.1", service.bound_port, secret=secret)
            bitmap = await rv.verify_batch(make_items(4, forge={1}))
            assert bitmap == [True, False, True, True]
            assert rv.remote_batches == 1 and rv.fallback_batches == 0
            await rv.close()

            # client without the secret: request rejected fast, local
            # fallback still verifies correctly (never trusts the network)
            rv2 = RemoteVerifier("127.0.0.1", service.bound_port, timeout_s=5.0)
            bitmap = await rv2.verify_batch(make_items(4, forge={2}))
            assert bitmap == [True, True, False, True]
            assert rv2.fallback_batches == 1
            await rv2.close()

            # client with a WRONG secret: its own MAC check rejects the
            # response path symmetrically -> fallback
            rv3 = RemoteVerifier(
                "127.0.0.1", service.bound_port, timeout_s=5.0, secret=bytes(32)
            )
            bitmap = await rv3.verify_batch(make_items(3))
            assert bitmap == [True, True, True]
            assert rv3.fallback_batches == 1
            await rv3.close()
        finally:
            await service.close()

    run(main())


def test_cluster_routes_cert_checks_through_shared_service():
    async def main():
        service = VerifierService(port=0, verifier=CpuVerifier())
        await service.start()
        port = service.bound_port
        try:
            async with VirtualCluster(
                4, rf=4,
                verifier_factory=lambda: RemoteVerifier("127.0.0.1", port),
            ) as vc:
                client = vc.client()
                await client.execute_write_transaction(
                    TransactionBuilder().write("svc-key", b"v").build()
                )
                res = await client.execute_read_transaction(
                    TransactionBuilder().read("svc-key").build()
                )
                assert res.operations[0].value == b"v"
                # every replica's envelope/cert checks went through the one
                # service process-equivalent
                assert service.requests >= 4
                for r in vc.replicas:
                    assert isinstance(r.verifier, RemoteVerifier)
                    assert r.verifier.fallback_batches == 0
        finally:
            await service.close()

    run(main())


def test_cluster_survives_service_death_and_recovery():
    """Kill the shared verifier service mid-traffic: replicas must fall
    back to local CPU verification (availability degrades, safety holds),
    and when a service returns on the same port they must resume routing
    through it — each RemoteVerifier retries the remote path per batch."""

    async def main():
        service = VerifierService(port=0, verifier=CpuVerifier())
        await service.start()
        port = service.bound_port
        async with VirtualCluster(
            4, rf=4,
            verifier_factory=lambda: RemoteVerifier("127.0.0.1", port),
        ) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("sd-1", b"a").build()
            )
            assert service.requests > 0

            # service dies mid-run
            await service.close()
            await client.execute_write_transaction(
                TransactionBuilder().write("sd-2", b"b").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("sd-2").build()
            )
            assert res.operations[0].value == b"b"
            assert any(
                r.verifier.fallback_batches > 0 for r in vc.replicas
            ), "no replica fell back while the service was down"

            # a new service on the SAME port: replicas resume using it
            service2 = VerifierService(port=port, verifier=CpuVerifier())
            await service2.start()
            try:
                await client.execute_write_transaction(
                    TransactionBuilder().write("sd-3", b"c").build()
                )
                res = await client.execute_read_transaction(
                    TransactionBuilder().read("sd-3").build()
                )
                assert res.operations[0].value == b"c"
                assert service2.requests > 0, "replicas never returned to the service"
            finally:
                await service2.close()

    run(main())


def test_service_status_counters_and_admin_endpoint():
    """status() reports request/item/cache counters, and the standalone
    CLI's --admin-port serves them as JSON over loopback HTTP."""
    import json
    import urllib.request

    from mochi_tpu.crypto import keys
    from mochi_tpu.verifier.service import ServiceAdminServer, VerifierService
    from mochi_tpu.verifier.spi import VerifyItem

    async def main():
        svc = VerifierService(port=0, verifier=CpuVerifier())
        await svc.start()
        admin = ServiceAdminServer(svc, port=0)
        await admin.start()
        try:
            rv = RemoteVerifier("127.0.0.1", svc.bound_port)
            kp = keys.generate_keypair()
            items = [VerifyItem(kp.public_key, b"s", kp.sign(b"s"))] * 6
            assert await rv.verify_batch(items) == [True] * 6
            await rv.close()

            st = svc.status()
            assert st["requests"] == 1 and st["items"] == 6
            vs = st["verifier"]
            assert vs["type"] == "CachingVerifier"
            assert vs["hits"] == 5 and vs["misses"] == 1
            assert vs["inner"]["type"] == "CpuVerifier"
            assert st["authenticated"] is False

            port = admin.bound_port
            raw = await asyncio.to_thread(
                lambda: urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=5
                ).read()
            )
            assert json.loads(raw) == st

            prom = (
                await asyncio.to_thread(
                    lambda: urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics.prom", timeout=5
                    ).read()
                )
            ).decode()
            assert 'mochi_verifier_service{name="requests"} 1' in prom
            assert 'mochi_verifier_service{name="items"} 6' in prom
            assert 'mochi_verifier_service{name="verifier_hits"} 5' in prom
        finally:
            await admin.close()
            await svc.close()

    run(main())


@pytest.mark.slow
def test_sharded_backend_over_cpu_mesh():
    """ShardedTpuBatchVerifier splits a mixed batch over the 8-device CPU
    mesh (conftest forces it) and returns the same bitmap the CPU verifier
    would — the production multi-chip path, not just the benchmark one."""
    import asyncio

    from mochi_tpu.crypto import keys
    from mochi_tpu.verifier.spi import VerifyItem
    from mochi_tpu.verifier.tpu import ShardedTpuBatchVerifier

    kp = keys.generate_keypair()
    items = []
    expect = []
    for i in range(50):
        msg = b"sh%d" % i
        sig = kp.sign(msg)
        if i % 6 == 2:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
            expect.append(False)
        else:
            expect.append(True)
        items.append(VerifyItem(kp.public_key, msg, sig))

    async def main():
        # min_device_items=0: force the mesh path (the inherited CPU
        # crossover would otherwise route this small batch to OpenSSL and
        # the test would never exercise shard_map)
        v = ShardedTpuBatchVerifier(max_delay_s=0.001, min_device_items=0)
        try:
            assert v.backend.n_devices == 8
            out = await v.verify_batch(items)
            assert out == expect
        finally:
            await v.close()

    asyncio.run(main())

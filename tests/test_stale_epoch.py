"""Stale-epoch replay regression tests at the store seam (round-11
satellite): a replica restarted WITHOUT resync (``restart_replica(
resync=False)`` — epochs reset to 0, state empty) must never let a
replayed old certificate overwrite a newer commit CLUSTER-WIDE, and its
reset-epoch grants must never help a stale-timestamp quorum form.

These tests PIN current behavior precisely, including its honest limit:
the restarted replica itself — state empty, epochs 0 — will locally accept
a replayed stale-but-valid certificate (it has nothing newer to compare
against; storage is in-memory as in the reference).  That blast radius is
<= f by the fault model, the quorum outvotes it on every read, and resync
repairs it; what would be a BUG is any of the three cluster-level
assertions below failing.
"""

import asyncio

from mochi_tpu.client import TransactionBuilder
from mochi_tpu.protocol import (
    Write2AnsFromServer,
    Write2ToServer,
)
from mochi_tpu.testing import VirtualCluster


def run(coro):
    asyncio.run(asyncio.wait_for(coro, timeout=120))


async def _commit_and_capture(client, key: str, value: bytes):
    """Commit one write and return (transaction, committed certificate) —
    the certificate rides the quorum read's OperationResult."""
    txn = TransactionBuilder().write(key, value).build()
    await client.execute_write_transaction(txn)
    res = await client.execute_read_transaction(
        TransactionBuilder().read(key).build()
    )
    cert = res.operations[0].current_certificate
    assert cert is not None
    return txn, cert


def test_stale_cert_replay_rejected_by_staleness_check():
    """Store seam, no restart: a replica holding the NEWER commit answers
    a replayed older certificate with current state — nothing regresses."""

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            txn1, cert1 = await _commit_and_capture(client, "se", b"old")
            txn2, cert2 = await _commit_and_capture(client, "se", b"new")

            replica = vc.replicas[0]
            sv = replica.store._get("se")
            epoch_before = sv.current_epoch
            response = replica.store.process_write2(Write2ToServer(cert1, txn1))
            # stale write2: answered with CURRENT state, not applied
            # (ref: InMemoryDataStore.java:594-598)
            assert isinstance(response, Write2AnsFromServer)
            assert response.result.operations[0].value == b"new"
            sv = replica.store._get("se")
            assert sv.value == b"new"
            assert sv.current_epoch == epoch_before

    run(main())


def test_replay_after_reset_restart_cannot_overwrite_cluster():
    """restart_replica(resync=False) resets epochs to 0; replaying the old
    certificate at the restarted replica rewinds only ITSELF (pinned — the
    <= f blast radius), the quorum read still returns the newer value, and
    resync repairs the replica to the newer commit, after which the replay
    bounces off the staleness check like anywhere else."""

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            txn1, cert1 = await _commit_and_capture(client, "rs", b"old")
            txn2, cert2 = await _commit_and_capture(client, "rs", b"new")

            victims = [sid for sid in sorted(vc.config.servers)
                       if vc.replica(sid).store.owns("rs")]
            victim = victims[0]
            fresh = await vc.restart_replica(victim, resync=False)
            assert fresh.store._get("rs") is None  # empty, epochs reset

            # Replay the OLD (validly signed) certificate straight at the
            # restarted replica: with no local state it applies — the
            # pinned current behavior this test documents.
            resp = await fresh.handle_envelope(
                client._envelope(Write2ToServer(cert1, txn1), "replay-1")
            )
            assert isinstance(resp.payload, Write2AnsFromServer)
            assert fresh.store._get("rs").value == b"old"

            # Cluster-level safety: the quorum outvotes the rewound member.
            res = await client.execute_read_transaction(
                TransactionBuilder().read("rs").build()
            )
            assert res.operations[0].value == b"new"

            # Repair: resync pulls the newer commit from peers...
            await fresh.resync()
            assert fresh.store._get("rs").value == b"new"
            # ...and the replayed certificate now bounces off staleness.
            resp = await fresh.handle_envelope(
                client._envelope(Write2ToServer(cert1, txn1), "replay-2")
            )
            assert isinstance(resp.payload, Write2AnsFromServer)
            assert resp.payload.result.operations[0].value == b"new"
            assert fresh.store._get("rs").value == b"new"

    run(main())


def test_reset_epoch_grants_cannot_anchor_a_stale_quorum():
    """After a reset restart the replica issues grants at epoch-0
    timestamps while the honest majority grants at advanced epochs: the
    client's timestamp-consistent subset can never include the stale
    grant in a 2f+1 quorum, so writes keep committing at FRESH timestamps
    and the committed value stays readable everywhere."""

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            # advance epochs on the key's replica set
            for i in range(3):
                await client.execute_write_transaction(
                    TransactionBuilder().write("eg", b"w%d" % i).build()
                )
            victims = [sid for sid in sorted(vc.config.servers)
                       if vc.replica(sid).store.owns("eg")]
            await vc.restart_replica(victims[0], resync=False)

            # the next write must still commit — the reset-epoch grant is
            # a timestamp outlier the subset drops (up to f outliers are
            # budgeted by 3f+1)
            await client.execute_write_transaction(
                TransactionBuilder().write("eg", b"final").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("eg").build()
            )
            assert res.operations[0].value == b"final"
            # every honest (non-restarted) in-set replica holds the commit
            # at a non-reset epoch
            for sid in victims[1:]:
                sv = vc.replica(sid).store._get("eg")
                assert sv is not None and sv.value == b"final"
                assert sv.current_epoch >= 2000

    run(main())

"""Live-adversary tests: a real ByzantineReplica SERVING inside a cluster
(testing/byzantine.py), safety invariants checked while it misbehaves
(testing/invariants.py), and the observability the attacks are supposed to
light up — the round-11 tentpole's tier-1 coverage.

Complements tests/test_byzantine.py, which forges messages at the wire:
here the adversary answers real traffic with validly-authenticated lies.
"""

import asyncio

import pytest

from mochi_tpu.client import TransactionBuilder
from mochi_tpu.protocol import (
    FailType,
    RequestFailedFromServer,
    Write1OkFromServer,
    Write1ToServer,
    Write2ToServer,
    WriteCertificate,
    transaction_hash,
)
from mochi_tpu.testing import InvariantChecker, VirtualCluster


def run(coro):
    asyncio.run(asyncio.wait_for(coro, timeout=120))


async def _workload(vc, checker, client, keys=4, sweeps=2, prefix="lk"):
    """Writes + read-backs with every ack recorded into the checker."""
    for s in range(sweeps):
        for k in range(keys):
            key = f"{prefix}-{k}"
            val = b"v%d" % s
            await client.execute_write_transaction(
                TransactionBuilder().write(key, val).build()
            )
            checker.record_ack(key, val)


def test_silent_replica_straggler_observability():
    """Satellite: under the silent attack every commit rides the
    early-quorum straggler path — fanout.straggler-timeout.<sid> counters
    must accrue on the client, and the ClientAdminServer fan-out table
    must carry a per-peer suspicion row for the silent replica."""

    async def main():
        async with VirtualCluster(5, rf=4, byzantine={"server-1": "silent"}) as vc:
            checker = InvariantChecker(vc.honest_replicas(), ["server-1"])
            checker.start(0.02)
            client = vc.client(timeout_s=1.0)
            await _workload(vc, checker, client, keys=4, sweeps=2)
            await checker.final_check(client)
            await checker.stop()
            assert checker.ok, checker.report()["violations"]
            # the straggler drain convicted the silent replica
            timeouts = client.metrics.counters.get(
                "fanout.straggler-timeout.server-1", 0
            )
            assert timeouts > 0, dict(client.metrics.counters)
            # ... and the client admin shell surfaces it as a per-peer row
            from mochi_tpu.admin import ClientAdminServer

            shell = ClientAdminServer(client)
            await shell.start()
            try:
                status, _, body = shell._route("/status")
                assert status == 200
                import json

                doc = json.loads(body)
                peer = doc["fanout"]["peers"]["server-1"]
                assert peer["straggler_timeout"] == timeouts
                _, _, page = shell._route("/")
                assert "server-1" in page and "straggler_timeout" in page
            finally:
                await shell.close()

    run(main())


def test_silent_replica_suspicion_redirects_trimmed_reads():
    """After the silent replica's suspicion score crosses the threshold,
    the trimmed read fan-out stops choosing it — reads no longer pay a
    timeout + full-union retry per trim that includes the mute peer."""

    async def main():
        async with VirtualCluster(5, rf=4, byzantine={"server-1": "silent"}) as vc:
            client = vc.client(timeout_s=0.5)
            for k in range(3):
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"sr-{k}", b"v").build()
                )
            # force the suspicion score past the threshold (the drain's
            # timeout marks land ~timeout_s after each early return)
            await asyncio.sleep(0.8)
            assert client._suspicion_score("server-1") > 2
            for k in range(3):
                targets = client._quorum_targets(
                    TransactionBuilder().read(f"sr-{k}").build()
                )
                assert "server-1" not in [sid for sid, _ in targets], targets

    run(main())


def test_equivocation_detected_on_honest_replicas():
    """A live equivocator (refusal flipped to a conflicting OK grant at
    the same timestamp) is CONVICTED once both validly-signed sides are
    presented: the grant ledger counts it, /status carries it, and the
    prom exposition grows a mochi_byzantine sample."""

    async def main():
        async with VirtualCluster(4, rf=4, byzantine={"server-1": "equivocate"}) as vc:
            client = vc.client()
            txn_a = TransactionBuilder().write("eq", b"A").build()
            txn_b = TransactionBuilder().write("eq", b"B").build()
            byz = vc.config.servers["server-1"]
            grants = []
            for i, txn in enumerate((txn_a, txn_b)):
                blind = client._write1_transaction(txn)
                env = client._envelope(
                    Write1ToServer(
                        client.client_id, blind, 77, transaction_hash(txn)
                    ),
                    f"w1-{i}",
                )
                resp = await client.pool.send_and_receive(byz, env)
                # honest behavior would REFUSE the second; the equivocator
                # grants both at the same timestamp
                assert isinstance(resp.payload, Write1OkFromServer), resp.payload
                grants.append(resp.payload.multi_grant)
            ts = [next(iter(mg.grants.values())).timestamp for mg in grants]
            assert ts[0] == ts[1], ts

            honest = vc.config.servers["server-0"]
            for i, (txn, mg) in enumerate(zip((txn_a, txn_b), grants)):
                env = client._envelope(
                    Write2ToServer(WriteCertificate({"server-1": mg}), txn),
                    f"w2-{i}",
                )
                await client.pool.send_and_receive(honest, env)
            replica = vc.replica("server-0")
            assert replica.byzantine_stats()["equivocations"].get("server-1", 0) >= 1

            from mochi_tpu.admin import AdminServer

            shell = AdminServer(replica)
            await shell.start()
            try:
                import json

                _, _, body = shell._route("/status")
                assert json.loads(body)["byzantine"]["equivocations"]["server-1"] >= 1
                _, _, prom = shell._route("/metrics.prom")
                assert 'mochi_byzantine{peer="server-1",stat="equivocations"' in prom
            finally:
                await shell.close()

    run(main())


def test_forged_grants_filtered_and_writes_survive():
    """forge-cert: garbage grant signatures + wrong hashes from one in-set
    replica.  Client-side grant validation must keep them out of every
    certificate (writes succeed without a BAD_CERTIFICATE round trip) and
    attribute the suspicion; read tallies outvote the forged values."""

    async def main():
        async with VirtualCluster(5, rf=4, byzantine={"server-1": "forge-cert"}) as vc:
            checker = InvariantChecker(vc.honest_replicas(), ["server-1"])
            checker.start(0.02)
            client = vc.client(timeout_s=2.0)
            await _workload(vc, checker, client, keys=4, sweeps=2, prefix="fg")
            for k in range(4):
                res = await client.execute_read_transaction(
                    TransactionBuilder().read(f"fg-{k}").build()
                )
                assert res.operations[0].value == b"v1"
            await checker.final_check(client)
            await checker.stop()
            assert checker.ok, checker.report()["violations"]
            sus = client.suspicion_stats().get("server-1", {})
            assert sus.get("bad-grant", 0) > 0, sus

    run(main())


def test_stale_replay_live_invariants_hold():
    """stale-replay: epoch-reset grants + stale read answers from a live
    replica.  The grant subset drops the stale timestamps (suspicion:
    grant-conflict), quorum reads outvote the stale values, and epochs on
    HONEST replicas never regress."""

    async def main():
        async with VirtualCluster(5, rf=4, byzantine={"server-1": "stale-replay"}) as vc:
            checker = InvariantChecker(vc.honest_replicas(), ["server-1"])
            checker.start(0.02)
            client = vc.client(timeout_s=2.0)
            await _workload(vc, checker, client, keys=4, sweeps=3, prefix="st")
            await checker.final_check(client)
            await checker.stop()
            assert checker.ok, checker.report()["violations"]
            sus = client.suspicion_stats().get("server-1", {})
            assert sus.get("grant-conflict", 0) > 0, sus

    run(main())


@pytest.mark.slow
def test_storm_under_partition_invariants_hold():
    """storm + netsim partition of an honest replica: adversarial Write1
    refusals, nudge floods, and a transient quorum dip — every ack taken
    during the churn must survive it."""

    async def main():
        from mochi_tpu.netsim import NetSim

        sim = NetSim.mesh(seed=8, rtt_ms=4.0, jitter_ms=0.5)
        async with VirtualCluster(
            5, rf=4, netsim=sim, byzantine={"server-1": "storm"}
        ) as vc:
            checker = InvariantChecker(vc.honest_replicas(), ["server-1"])
            checker.start(0.02)
            client = vc.client(timeout_s=2.0)

            async def churn():
                await asyncio.sleep(0.15)
                for ev in NetSim.partition("server-3", 0.0):
                    sim.apply_event(ev)
                await asyncio.sleep(0.5)
                for ev in NetSim.heal("server-3"):
                    sim.apply_event(ev)

            task = asyncio.ensure_future(churn())
            await _workload(vc, checker, client, keys=3, sweeps=4, prefix="sp")
            await task
            await checker.final_check(client)
            await checker.stop()
            assert checker.ok, checker.report()["violations"]

    run(main())


def test_invariant_checker_is_not_vacuous():
    """The checker must actually catch violations: regress an honest
    replica's store by hand (epoch rollback + conflicting commit at an
    already-committed timestamp) and demand both invariants fire."""

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            checker = InvariantChecker(vc.replicas)
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("nv", b"v0").build()
            )
            checker.check_now()
            assert checker.ok
            replica = vc.replicas[0]
            sv = replica.store._get("nv")
            assert sv is not None and sv.current_certificate is not None
            # epoch regression
            sv.current_epoch = 0
            checker.check_now()
            # conflicting commit: same certificate timestamps, different txn
            sv.last_transaction = TransactionBuilder().write("nv", b"evil").build()
            checker.check_now()
            report = checker.report()
            assert not report["ok"]
            kinds = " ".join(report["violations"])
            assert "regression" in kinds and "conflicting commits" in kinds

    run(main())


def test_colluding_replicas_rf7_f2_invariants_hold():
    """Colluding adversaries WITHIN the fault bound at larger rf: rf=7 →
    f=2, quorum=5, with two coordinated attackers (an equivocator and a
    cert-forger) serving live traffic.  Writes must converge through the
    5 honest replicas, forged grants must be filtered client-side, and
    every safety invariant must hold at f=2."""

    async def main():
        async with VirtualCluster(
            7,
            rf=7,
            byzantine={"server-1": "equivocate", "server-2": "forge-cert"},
        ) as vc:
            assert vc.config.f == 2 and vc.config.quorum == 5
            checker = InvariantChecker(
                vc.honest_replicas(), ["server-1", "server-2"]
            )
            checker.start(0.02)
            client = vc.client(timeout_s=2.0)
            await _workload(vc, checker, client, keys=4, sweeps=2, prefix="f2")
            for k in range(4):
                res = await client.execute_read_transaction(
                    TransactionBuilder().read(f"f2-{k}").build()
                )
                assert res.operations[0].value == b"v1"
            await checker.final_check(client)
            await checker.stop()
            assert checker.ok, checker.report()["violations"]
            # the forger's garbage grants were filtered and attributed
            sus = client.suspicion_stats().get("server-2", {})
            assert sus.get("bad-grant", 0) > 0, client.suspicion_stats()

    run(main())


def test_checker_convicts_when_fault_bound_exceeded_f3():
    """Checker non-vacuity AT SCALE: with f+1=3 colluding equivocators in
    an rf=7 (f=2) cluster, two conflicting transactions can each assemble
    a legitimate-looking 5-grant certificate for the SAME (key, ts) slot
    — 2 honest grants + 3 equivocated each — and commit on disjoint
    honest replicas.  Safety is genuinely violated, and the
    InvariantChecker must say so (a checker that stays green past the
    fault bound proves nothing within it)."""

    async def main():
        byz_ids = ["server-1", "server-2", "server-6"]
        async with VirtualCluster(
            7, rf=7, byzantine={sid: "equivocate" for sid in byz_ids}
        ) as vc:
            client = vc.client(timeout_s=2.0)
            txn_a = TransactionBuilder().write("ovr", b"A").build()
            txn_b = TransactionBuilder().write("ovr", b"B").build()
            halves = {id(txn_a): ["server-0", "server-3"],
                      id(txn_b): ["server-4", "server-5"]}
            certs = {}
            for txn in (txn_a, txn_b):
                blind = client._write1_transaction(txn)
                grants = []
                for sid in halves[id(txn)] + byz_ids:
                    env = client._envelope(
                        Write1ToServer(
                            client.client_id, blind, 77, transaction_hash(txn)
                        ),
                        f"f3-w1-{sid}-{id(txn)}",
                    )
                    resp = await client.pool.send_and_receive(
                        vc.config.servers[sid], env
                    )
                    # honest replicas that never saw the other txn grant
                    # genuinely; the equivocators flip their refusals
                    assert isinstance(resp.payload, Write1OkFromServer), (
                        sid, resp.payload
                    )
                    grants.append(resp.payload.multi_grant)
                ts = {
                    mg.grants["ovr"].timestamp for mg in grants
                }
                assert len(ts) == 1, ts  # one slot, both transactions
                certs[id(txn)] = WriteCertificate(
                    {mg.server_id: mg for mg in grants}
                )
            checker = InvariantChecker(vc.honest_replicas(), byz_ids)
            # commit A on one honest pair, B on the other: disjoint honest
            # replicas now hold conflicting certificates for one slot
            for txn in (txn_a, txn_b):
                for sid in halves[id(txn)]:
                    env = client._envelope(
                        Write2ToServer(certs[id(txn)], txn),
                        f"f3-w2-{sid}-{id(txn)}",
                    )
                    await client.pool.send_and_receive(
                        vc.config.servers[sid], env
                    )
            checker.check_now()
            report = checker.report()
            assert not report["ok"], "checker vacuous past the fault bound"
            assert any("conflicting commits" in v for v in report["violations"])
            # presenting BOTH certificates to one honest replica also
            # convicts the equivocators cryptographically (grant ledger)
            for txn in (txn_a, txn_b):
                env = client._envelope(
                    Write2ToServer(certs[id(txn)], txn),
                    f"f3-ev-{id(txn)}",
                )
                await client.pool.send_and_receive(
                    vc.config.servers["server-0"], env
                )
            eq = vc.replica("server-0").byzantine_stats()["equivocations"]
            assert any(eq.get(sid, 0) >= 1 for sid in byz_ids), eq

    run(main())


def test_process_cluster_byzantine_silent_commits_cross_process():
    """ByzantineReplica across a REAL process boundary: ProcessCluster
    forwards --byzantine to the hosting child, the silent child answers
    nothing, and commits still land through the early-quorum path."""

    async def main():
        from mochi_tpu.testing import ProcessCluster

        async with ProcessCluster(
            4, rf=4, n_processes=2, byzantine={"server-1": "silent"}
        ) as pc:
            client = pc.client(timeout_s=0.8)
            await client.execute_write_transaction(
                TransactionBuilder().write("pb", b"v").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("pb").build()
            )
            assert res.operations[0].value == b"v"
            # the straggler drain's timeout verdicts land ~timeout_s after
            # each early return — wait them out before asserting
            await asyncio.sleep(1.2)
            assert (
                client.metrics.counters.get("fanout.straggler-timeout.server-1", 0)
                + client.metrics.counters.get("suspect.no-response.server-1", 0)
                > 0
            ), dict(client.metrics.counters)

    run(main())


# ------------------------------------------------------------------ round 18
# Fast-path downgrade/tamper probes (session-attack strategy): every attack
# on the MAC session machinery must end in a TYPED refusal or a conviction
# with flight-recorder evidence — never a silent fallback.


def test_session_attack_mac_tamper_typed_refusal_and_conviction(tmp_path):
    """MAC-window mutation: a sealed envelope whose payload was swapped
    after sealing gets a typed BAD_SIGNATURE, a mac-tamper conviction mark,
    and a flight-recorder dump naming the evidence."""

    async def main():
        async with VirtualCluster(
            4, rf=4, byzantine={"server-1": "session-attack"}
        ) as vc:
            victim = vc.replica("server-0")
            victim.tracer.flight_dir = str(tmp_path)
            strat = vc.replica("server-1").strategy
            res = await strat.tamper_mac_window("server-0")
            assert isinstance(res.payload, RequestFailedFromServer), res.payload
            assert res.payload.fail_type == FailType.BAD_SIGNATURE
            assert victim.metrics.counters.get("replica.mac-tamper", 0) >= 1
            dumps = list(tmp_path.glob("flight-*.json"))
            assert dumps, "conviction must leave flight-recorder evidence"
            assert any("mac-tamper" in p.read_text() for p in dumps)

    run(main())


def test_session_attack_replay_across_window_convicted(tmp_path):
    """Cross-checkpoint replay: one sealed envelope delivered twice but
    signed for once.  Both deliveries authenticate (the MAC is genuine);
    the signed transcript then under-covers the victim's ledger — a
    checkpoint-mismatch conviction, flight evidence, and the session
    drops on BOTH sides."""

    async def main():
        async with VirtualCluster(
            4, rf=4, byzantine={"server-1": "session-attack"}
        ) as vc:
            victim = vc.replica("server-0")
            victim.tracer.flight_dir = str(tmp_path)
            byz = vc.replica("server-1")
            first, second = await byz.strategy.replay_across_window("server-0")
            assert not isinstance(first.payload, RequestFailedFromServer)
            assert not isinstance(second.payload, RequestFailedFromServer)
            assert victim.metrics.counters.get(
                "replica.checkpoint-mismatch", 0
            ) >= 1
            # the refusal was typed back to the (Byzantine) sender, which
            # dropped its side of the session per the honest-sender contract
            assert byz.metrics.counters.get(
                "replica.peer-checkpoint-refused", 0
            ) >= 1
            assert "server-1" not in victim._sessions
            assert "server-0" not in byz._peer_sessions
            dumps = list(tmp_path.glob("flight-*.json"))
            assert any("checkpoint-mismatch" in p.read_text() for p in dumps)

    run(main())


def test_session_attack_downgrade_checkpoint_refused_typed(tmp_path):
    """Forced signature→MAC downgrade: a transcript declaration arriving
    under session MAC (forgeable by whoever holds the session key) must be
    refused typed (BAD_REQUEST, named detail) and convicted — the silent
    acceptance would void the whole retroactive-conviction design."""

    async def main():
        async with VirtualCluster(
            4, rf=4, byzantine={"server-1": "session-attack"}
        ) as vc:
            victim = vc.replica("server-0")
            victim.tracer.flight_dir = str(tmp_path)
            strat = vc.replica("server-1").strategy
            res = await strat.downgrade_checkpoint("server-0")
            assert isinstance(res.payload, RequestFailedFromServer), res.payload
            assert res.payload.fail_type == FailType.BAD_REQUEST
            assert "Ed25519-signed" in res.payload.detail
            assert victim.metrics.counters.get(
                "replica.checkpoint-downgrade", 0
            ) >= 1
            dumps = list(tmp_path.glob("flight-*.json"))
            assert any("checkpoint-downgrade" in p.read_text() for p in dumps)

    run(main())


def test_session_attack_overdue_flood_typed_policy_refusal(monkeypatch):
    """Riding the MAC discount without ever signing a declaration: past
    OVERDUE_FACTOR checkpoint windows the victim refuses typed
    (BAD_REQUEST policy refusal, not BAD_SIGNATURE — there is no forgery)
    and drops the session so the sender must re-handshake."""
    from mochi_tpu.crypto import session as session_crypto

    monkeypatch.setattr(session_crypto, "CHECKPOINT_MSGS", 2)

    async def main():
        async with VirtualCluster(
            4, rf=4, byzantine={"server-1": "session-attack"}
        ) as vc:
            victim = vc.replica("server-0")
            strat = vc.replica("server-1").strategy
            # cap = OVERDUE_FACTOR (4) * CHECKPOINT_MSGS (2) = 8 accepted
            # MAC'd envelopes; the 9th is the policy refusal
            last = await strat.overdue_flood("server-0", n=9)
            assert isinstance(last.payload, RequestFailedFromServer), last.payload
            assert last.payload.fail_type == FailType.BAD_REQUEST
            assert "overdue" in last.payload.detail
            assert victim.metrics.counters.get(
                "replica.checkpoint-overdue", 0
            ) >= 1
            assert "server-1" not in victim._sessions

    run(main())

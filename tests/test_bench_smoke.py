"""Benchmark-harness smoke (tier-1): ``run_all --smoke`` must produce an
error-free, provenance-stamped record from ALL 14 configs in seconds.

This is rot detection, not measurement: a benchmark that imports a moved
module, calls a renamed API, or drifts its record schema fails HERE, at
PR time, instead of during the next publish battery.  Smoke numbers are
meaningless by construction (tiny counts, eager execution, stubbed device
verify program — see run_all._run_child) and --publish is refused.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ENGINES = ("openssl", "native-c", "pure-python")


def _run(args, timeout):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "benchmarks.run_all", *args],
        cwd=REPO, capture_output=True, text=True, timeout=timeout, env=env,
    )


def test_run_all_smoke_covers_all_fourteen_configs():
    proc = _run(["--smoke"], timeout=700)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-800:]
    recs = [
        json.loads(line)
        for line in proc.stdout.splitlines()
        if line.startswith("{")
    ]
    by_config = {r.get("config"): r for r in recs}
    # configs 1-14: 14 (paged value engine) joined in round 17
    assert sorted(by_config, key=int) == [
        str(i) for i in range(1, 15)
    ], sorted(by_config)
    for key, rec in sorted(by_config.items()):
        assert not rec.get("error"), (key, rec)
        assert "metric" in rec and "value" in rec, (key, rec)
        # the provenance satellite: every record names its host engine
        assert rec.get("host_crypto_engine") in _ENGINES, (key, rec)
        # round-15 tracing satellite: every record carries a non-empty
        # trace_summary (tracing is FORCED to sample 1.0 for smoke), and
        # the configs that drive an in-process cluster must have actually
        # RECORDED spans — a span-recording seam rotting away fails HERE,
        # at PR time, not at the next publish battery.
        ts = rec.get("trace_summary")
        assert isinstance(ts, dict) and ts, (key, rec)
        for field in ("enabled", "sample_rate", "spans_recorded"):
            assert field in ts, (key, ts)
        if key in ("1", "3", "4", "6", "7", "9", "10", "11", "13"):
            assert ts["enabled"] and ts["spans_recorded"] > 0, (key, ts)


def test_smoke_refuses_publish():
    proc = _run(["--smoke", "--publish"], timeout=60)
    assert proc.returncode == 2
    assert "meaningless" in proc.stderr


def test_smoke_wire_taint_preflight_passes_on_clean_tree():
    # the preflight itself (PR 16): a clean tree sails through — no exit
    from benchmarks.run_all import _wire_taint_preflight

    _wire_taint_preflight()


def test_smoke_wire_taint_preflight_blocks_dirty_tree(monkeypatch, capsys):
    """A fast-path PR that bypasses the verifier registry must fail the
    smoke leg at PR time: a wire-taint finding (registry-rot or a fresh
    unverified flow) exits 4 before any benchmark child spawns."""
    import pytest

    import mochi_tpu.analysis.core as analysis_core
    from benchmarks.run_all import _wire_taint_preflight

    dirty = analysis_core.RunResult(
        new=[
            analysis_core.Finding(
                "wire-taint", "mochi_tpu/server/replica.py", 1, 0,
                "registry-rot: sanctioned edge 'session-mac' matched no "
                "call site",
                snippet="registry-rot:session-mac",
            )
        ]
    )
    monkeypatch.setattr(analysis_core, "run", lambda *a, **k: dirty)
    monkeypatch.delenv("MOCHI_SKIP_LINT", raising=False)
    with pytest.raises(SystemExit) as exc:
        _wire_taint_preflight()
    assert exc.value.code == 4
    assert "register its verifier edge" in capsys.readouterr().err

"""netsim unit + integration coverage (ISSUE 4 tentpole).

The deterministic core (`LinkPolicy.plan`) is tested without a cluster or
an event loop: same seed => identical per-link (fate, delay) sequences.
Schedule semantics (partition blocks exactly the scheduled links, heal
restores, late-created links inherit fired events) drive `apply_event`
directly.  One integration test runs a conditioned VirtualCluster end to
end and checks the conditioning is (a) applied — read latency >= ~1 RTT —
and (b) observable on /status and /metrics.prom.
"""

import asyncio
import json
import urllib.request

from mochi_tpu.netsim import LinkEvent, LinkSpec, NetSim


def _plans(sim: NetSim, src: str, dst: str, n: int = 64, size: int = 512):
    pol = sim.policy(src, dst)
    return [pol.plan(size, now=float(i)) for i in range(n)]


# ------------------------------------------------------------- determinism


def test_same_seed_identical_delay_sequence():
    spec = dict(rtt_ms=13.0, jitter_ms=2.0, drop=0.1, reorder=0.05)
    a = _plans(NetSim.mesh(seed=8, **spec), "client-0", "server-1")
    b = _plans(NetSim.mesh(seed=8, **spec), "client-0", "server-1")
    assert a == b
    assert any(fate == "drop" for fate, _ in a)  # the stream exercises drop
    assert any(d > 0 for _, d in a)


def test_different_seed_differs():
    spec = dict(rtt_ms=13.0, jitter_ms=2.0)
    a = _plans(NetSim.mesh(seed=8, **spec), "a", "b")
    b = _plans(NetSim.mesh(seed=9, **spec), "a", "b")
    assert a != b


def test_per_link_streams_independent():
    """Traffic on one link must not perturb another link's stream: the
    a->b sequence is identical whether or not c->d drew frames first."""
    spec = dict(rtt_ms=13.0, jitter_ms=2.0, drop=0.2)
    quiet = NetSim.mesh(seed=8, **spec)
    noisy = NetSim.mesh(seed=8, **spec)
    _plans(noisy, "c", "d", n=37)  # unrelated traffic first
    assert _plans(quiet, "a", "b") == _plans(noisy, "a", "b")
    # and the two directions of one pair are distinct streams
    assert _plans(quiet, "a", "b") != _plans(quiet, "b", "a")


# ---------------------------------------------------------------- ordering


def test_fifo_preserved_without_reorder():
    sim = NetSim.mesh(seed=8, rtt_ms=13.0, jitter_ms=6.0)
    pol = sim.policy("a", "b")
    arrivals = []
    now = 0.0
    for _ in range(200):
        fate, delay = pol.plan(256, now=now)
        assert fate == "deliver"
        arrivals.append(now + delay)
        now += 0.001  # frames sent 1 ms apart; jitter spans ±3 ms one-way
    assert arrivals == sorted(arrivals)


def test_reorder_drawn_and_counted():
    sim = NetSim.mesh(seed=8, rtt_ms=10.0, reorder=1.0)
    pol = sim.policy("a", "b")
    fate, delay = pol.plan(256, now=0.0)
    assert fate == "reorder"
    # held back at least one extra propagation delay vs the base one-way
    assert delay > 5.0 / 1e3


def test_bandwidth_serialization_queues():
    # 8 kbit/s link, 1000-byte frames: 1 s serialization each, queuing
    # behind one another when sent back-to-back.
    sim = NetSim(seed=8, default=LinkSpec(bandwidth_bps=8000.0))
    pol = sim.policy("a", "b")
    _, d1 = pol.plan(1000, now=0.0)
    _, d2 = pol.plan(1000, now=0.0)
    assert abs(d1 - 1.0) < 1e-6
    assert abs(d2 - 2.0) < 1e-6


# ------------------------------------------------------------- spec lookup


def test_spec_resolution_precedence():
    default = LinkSpec(delay_ms=1.0)
    exact = LinkSpec(delay_ms=2.0)
    to_b = LinkSpec(delay_ms=3.0)
    from_a = LinkSpec(delay_ms=4.0)
    sim = NetSim(
        seed=0,
        default=default,
        links={("a", "b"): exact, ("*", "b"): to_b, ("a", "*"): from_a},
    )
    assert sim.policy("a", "b").spec is exact
    assert sim.policy("c", "b").spec is to_b
    assert sim.policy("a", "c").spec is from_a
    assert sim.policy("c", "d").spec is default


# ----------------------------------------------------- schedules/partitions


def test_partition_blocks_exactly_the_scheduled_links():
    sim = NetSim.mesh(seed=8, rtt_ms=13.0)
    ab = sim.policy("a", "b")
    ba = sim.policy("b", "a")
    ac = sim.policy("a", "c")
    for ev in NetSim.partition("b", at_s=0.0):
        sim.apply_event(ev)
    assert ab.down and ba.down and not ac.down
    assert ab.plan(64, now=0.0) == ("drop", 0.0)
    assert ac.plan(64, now=0.0)[0] == "deliver"
    # heal restores both directions
    for ev in (LinkEvent(0.0, "up", "b", "*"), LinkEvent(0.0, "up", "*", "b")):
        sim.apply_event(ev)
    assert not ab.down and not ba.down
    assert ab.plan(64, now=100.0)[0] == "deliver"


def test_wildcard_up_heals_specific_downs():
    """An `up` clears every down pattern it covers: heal-all ("*", "*")
    must heal a node partition recorded as specific patterns, and a node
    heal must clear that node's per-link downs."""
    sim = NetSim.mesh(seed=8, rtt_ms=13.0)
    ab = sim.policy("a", "b")
    ba = sim.policy("b", "a")
    for ev in NetSim.partition("b", at_s=0.0):
        sim.apply_event(ev)
    assert ab.down and ba.down
    sim.apply_event(LinkEvent(0.0, "up", "*", "*"))  # heal-all
    assert not ab.down and not ba.down
    # node heal covers a per-link down of that node
    sim.apply_event(LinkEvent(0.0, "down", "b", "a"))
    assert ba.down
    sim.apply_event(LinkEvent(0.0, "up", "b", "*"))
    assert not ba.down


def test_late_created_link_inherits_fired_events():
    """Links materialize lazily on first connection — a partition that
    fired before the link existed must still block it."""
    sim = NetSim.mesh(seed=8, rtt_ms=13.0)
    for ev in NetSim.partition("b", at_s=0.0):
        sim.apply_event(ev)
    assert sim.policy("z", "b").down  # created after the event
    assert not sim.policy("z", "c").down


def test_degrade_uplink_set_and_reset():
    slow = LinkSpec(delay_ms=100.0, drop=0.5)
    sim = NetSim.mesh(seed=8, rtt_ms=13.0)
    pol = sim.policy("server-2", "client-0")
    base = pol.spec
    sim.apply_event(LinkEvent(0.0, "set", "server-2", "*", slow))
    assert pol.spec is slow
    sim.apply_event(LinkEvent(0.0, "reset", "server-2", "*"))
    assert pol.spec is base


def test_schedule_arms_lazily_and_rearms_after_close():
    """Standalone postures (client-only netsim against live servers)
    never call ensure_started — the first on-loop link_pair must arm the
    schedule; close() resets link state so a reused sim re-arms from a
    fresh t=0 instead of silently running with a dead schedule."""

    async def main():
        sim = NetSim.mesh(
            seed=1, rtt_ms=1.0,
            schedule=NetSim.partition("b", at_s=0.05),
        )
        assert sim.link_pair("a", "b") is not None  # arms the schedule
        await asyncio.sleep(0.15)
        assert sim.policy("a", "b").down and sim.policy("b", "a").down
        sim.close()
        assert not sim.policy("a", "b").down  # close resets link state
        # second use: schedule re-arms relative to a new t=0
        sim.link_pair("a", "b")
        assert not sim.policy("a", "b").down
        await asyncio.sleep(0.15)
        assert sim.policy("a", "b").down
        sim.close()

    asyncio.run(asyncio.wait_for(main(), timeout=30))


def test_undeliverable_frame_counts_lost_not_delivered():
    """Egress to a transport that closed while the frame was in flight
    reports False; the link must count it `lost` — `delivered == frames`
    is the evidence records' lossless observable and must not lie."""

    async def main():
        sim = NetSim.mesh(seed=1, rtt_ms=2.0)
        pol = sim.policy("a", "b")
        got = []
        pol.send(lambda f: got.append(f) or True, b"ok")
        pol.send(lambda f: False, b"gone")  # closed-transport analog
        await asyncio.sleep(0.01)
        sim.close()
        s = pol.stats()
        assert got == [b"ok"]
        assert s["frames"] == 2 and s["delivered"] == 1 and s["lost"] == 1

    asyncio.run(asyncio.wait_for(main(), timeout=30))


# ------------------------------------------------------------- passthrough


def test_disabled_netsim_hands_out_no_policies():
    sim = NetSim.mesh(seed=8, rtt_ms=13.0, enabled=False)
    assert sim.policy("a", "b") is None
    assert sim.link_pair("a", "b") is None
    assert sim.stats()["links"] == {}


def test_disabled_cluster_transport_takes_null_path():
    """With netsim attached-but-disabled, protocols carry no link policies
    (the `link is None` fast path — the passthrough leg of the config-7
    overhead A/B)."""

    async def main():
        from mochi_tpu.admin import AdminServer
        from mochi_tpu.client.txn import TransactionBuilder
        from mochi_tpu.testing.virtual_cluster import VirtualCluster

        sim = NetSim.mesh(seed=8, rtt_ms=13.0, enabled=False)
        async with VirtualCluster(4, rf=4, netsim=sim) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("pt", b"v").build()
            )
            conns = list(client.pool._connections.values())
            assert conns and all(c.links is None for c in conns)
            for conn in conns:
                assert conn._proto.egress_link is None
                assert conn._proto.ingress_link is None
            # admin surfaces of the disabled leg must be indistinguishable
            # from a replica with no netsim at all
            admin = AdminServer(vc.replicas[0], port=0)
            await admin.start()
            try:
                loop = asyncio.get_running_loop()
                _, body = await loop.run_in_executor(
                    None, _get, admin.bound_port, "/status"
                )
                assert "netsim" not in json.loads(body)
                _, prom = await loop.run_in_executor(
                    None, _get, admin.bound_port, "/metrics.prom"
                )
                assert "mochi_netsim" not in prom
            finally:
                await admin.close()
        assert sim.totals()["frames"] == 0

    asyncio.run(asyncio.wait_for(main(), timeout=120))


# ------------------------------------------------------------- integration


def _get(port: int, path: str):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.status, resp.read().decode()


def test_conditioned_cluster_end_to_end_with_admin_surfaces():
    async def main():
        from mochi_tpu.admin import AdminServer
        from mochi_tpu.client.txn import TransactionBuilder
        from mochi_tpu.testing.virtual_cluster import VirtualCluster

        import time

        sim = NetSim.mesh(seed=8, rtt_ms=6.0, jitter_ms=0.5)
        async with VirtualCluster(5, rf=4, netsim=sim) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("wan", b"v").build()
            )
            t0 = time.perf_counter()
            res = await client.execute_read_transaction(
                TransactionBuilder().read("wan").build()
            )
            read_s = time.perf_counter() - t0
            assert res.operations[0].value == b"v"
            # one conditioned round trip is the latency floor
            assert read_s >= 0.005, read_s
            totals = sim.totals()
            assert totals["delayed"] > 0 and totals["dropped"] == 0

            admin = AdminServer(vc.replicas[0], port=0)
            await admin.start()
            try:
                loop = asyncio.get_running_loop()
                _, body = await loop.run_in_executor(
                    None, _get, admin.bound_port, "/status"
                )
                doc = json.loads(body)
                assert doc["netsim"]["seed"] == 8
                links = doc["netsim"]["links"]
                assert any(v["delivered"] > 0 for v in links.values())
                _, prom = await loop.run_in_executor(
                    None, _get, admin.bound_port, "/metrics.prom"
                )
                assert 'mochi_netsim{link="' in prom
                assert 'stat="delivered"' in prom
            finally:
                await admin.close()

    asyncio.run(asyncio.wait_for(main(), timeout=120))

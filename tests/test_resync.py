"""State-transfer / UptoSpeed resync tests.

The failure these guard: replica state is in-memory (as in the reference), so
a restarted replica rejoins with epoch 0 for every key; its Write1 grants can
then never match the surviving quorum's timestamps and writes to warm keys
refuse forever.  The reference paper declares a client-initiated "UptoSpeed"
recovery (``mochiDB.tex:168-169``) but never implemented it; here it exists
in both flavors: explicit pull (``MochiReplica.resync``) and client-nudged
background sync on timestamp-split retries.
"""

import asyncio
from dataclasses import replace

from mochi_tpu.client import TransactionBuilder
from mochi_tpu.protocol import Grant, MultiGrant, SyncEntry, WriteCertificate
from mochi_tpu.testing import VirtualCluster


def run(coro):
    asyncio.run(asyncio.wait_for(coro, timeout=120))


def test_restart_then_explicit_resync_recovers_writes():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("warm", b"v1").build()
            )
            # two restarts: beyond f=1, writes to the warm key cannot reach a
            # timestamp-consistent quorum until the replicas resync
            r1 = await vc.restart_replica("server-0")
            r2 = await vc.restart_replica("server-1")
            assert r1.store.stats()["keys"] == 0

            advanced = await r1.resync()
            assert advanced >= 1
            advanced = await r2.resync()
            assert advanced >= 1

            # epochs and certificates are back: a fresh write converges
            await client.execute_write_transaction(
                TransactionBuilder().write("warm", b"v2").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("warm").build()
            )
            assert res.operations[0].value == b"v2"
            # recovered replica serves the certified value locally too
            sv = r1.store.data.get("warm")
            assert sv is not None and sv.current_certificate is not None

    run(main())


def test_client_nudge_triggers_background_resync():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client(refusal_retries=12, write_attempts=24)
            await client.execute_write_transaction(
                TransactionBuilder().write("hotkey", b"a").build()
            )
            # advance the epoch so laggards are >= one epoch behind
            await client.execute_write_transaction(
                TransactionBuilder().write("hotkey", b"b").build()
            )
            await vc.restart_replica("server-2")
            # no explicit resync: the write retry loop must detect the
            # timestamp split, nudge, and eventually converge
            await client.execute_write_transaction(
                TransactionBuilder().write("hotkey", b"c").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("hotkey").build()
            )
            assert res.operations[0].value == b"c"

    run(main())


def test_resync_rejects_forged_entries():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("truth", b"honest").build()
            )
            victim = await vc.restart_replica("server-0")

            # Byzantine peer hands the recovering replica a forged entry:
            # right shape, no valid quorum signatures
            honest = vc.replica("server-1")
            [entry] = honest.store.export_sync_entries(["truth"])
            forged_grants = {}
            for sid, mg in entry.certificate.grants.items():
                forged_grants[sid] = replace(mg, signature=b"\x00" * 64)
            forged = SyncEntry(
                "truth", entry.transaction, WriteCertificate(forged_grants)
            )
            checked = await victim._check_certificate(forged.certificate)
            assert checked is None  # all grants dropped -> nothing to apply

            # the real entry, by contrast, applies cleanly
            checked = await victim._check_certificate(entry.certificate)
            assert checked is not None
            assert victim.store.apply_sync_entry(
                replace(entry, certificate=checked)
            )
            assert victim.store.data["truth"].value == b"honest"

    run(main())


def test_sync_request_served_only_for_owned_committed_keys():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("k1", b"x").write("k2", b"y").build()
            )
            replica = vc.replica("server-3")
            entries = replica.store.export_sync_entries()
            keys = {e.key for e in entries}
            assert {"k1", "k2"} <= keys
            for e in entries:
                assert e.certificate.grants  # every entry carries its proof
                assert any(op.key == e.key for op in e.transaction.operations)
            # unknown keys produce nothing
            assert replica.store.export_sync_entries(["nope"]) == []

    run(main())


def test_read_quorum_failure_recovers_via_client_nudge():
    """Two replicas of a key's set restart EMPTY (no --resync-on-boot):
    the remaining holders can no longer outvote them, so the first read
    attempt quorum-fails — the client must nudge the set to resync and
    retry, returning the committed value instead of InconsistentRead
    (found live in round-3 verification; reads previously never nudged)."""

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("warm", b"v1").build()
            )
            r1 = await vc.restart_replica("server-0")
            r2 = await vc.restart_replica("server-1")
            assert r1.store.stats()["keys"] == 0
            assert r2.store.stats()["keys"] == 0
            # no explicit resync, no write-side nudge: the READ must recover
            res = await client.execute_read_transaction(
                TransactionBuilder().read("warm").build()
            )
            assert res.operations[0].value == b"v1"

    run(main())

"""Deterministic schedule explorer: replayability + the two hottest windows.

Three layers (docs/ANALYSIS.md §schedule):

1. the replayability PROPERTY — same seed ⇒ byte-identical event order and
   identical verdict, three runs in a row;
2. a planted async-TOCTOU race the stock FIFO loop never trips: the
   explorer must FIND a failing seed and REPLAY it exactly (same error,
   same trace) — the "reproduction, not anecdote" contract;
3. the two windows the static pass ranks hottest, driven through REAL
   replica/store code with no sockets (so schedules stay deterministic):
   handle_batch→session-eviction (PR-8's pin fix) and
   Write1→reclaim→Write2 (PR-9's grant-TTL reclamation).  Fast single-seed
   legs run in tier-1; the multi-seed exploration legs are slow-marked
   (``MOCHI_SCHED_SEEDS`` widens them).
"""

import asyncio

import pytest

from mochi_tpu.testing import schedule


# ------------------------------------------------------------ replayability


class _Workload:
    """Deterministic-but-schedule-sensitive: tasks contend on a shared dict
    with yields between check and act, all via tolerant operations (no
    crash) — the TRACE is what varies across seeds."""

    def __init__(self):
        self.table = {}
        self.log = []

    async def worker(self, wid):
        for i in range(5):
            self.table[wid] = i
            await asyncio.sleep(0)
            self.log.append((wid, self.table.get(wid)))
            self.table.pop(wid, None)
            await asyncio.sleep(0)


def _workload_case():
    async def case():
        w = _Workload()
        await asyncio.gather(*(w.worker(i) for i in range(4)))

    return case()


def test_same_seed_three_runs_byte_identical():
    runs = [schedule.run_case(_workload_case, seed=5) for _ in range(3)]
    assert all(r.ok for r in runs), [r.error for r in runs]
    traces = {r.trace_bytes() for r in runs}
    assert len(traces) == 1, "same seed must replay byte-identically"
    assert len(runs[0].trace) > 10  # non-vacuous: the loop really traced


def test_distinct_seeds_explore_distinct_orders():
    results = [schedule.run_case(_workload_case, seed=s) for s in range(8)]
    assert all(r.ok for r in results)
    assert len({r.trace_bytes() for r in results}) > 1, (
        "the seed must actually perturb wake order"
    )


# ------------------------------------------------------------- planted race


class _Evictable:
    """The SessionTable-eviction bug shape, distilled: victim checks, then
    acts one await later; a concurrent evictor may have removed the entry
    in between.  FIFO wake order happens to run the victim first — only a
    perturbed schedule exposes the KeyError."""

    def __init__(self):
        self.table = {"k": 1}

    async def victim(self):
        if "k" in self.table:
            await asyncio.sleep(0)
            del self.table["k"]  # mochi-lint: disable=await-races -- the PLANTED bug this test exists to catch dynamically

    async def evictor(self):
        await asyncio.sleep(0)
        self.table.pop("k", None)


def _planted_case():
    async def case():
        s = _Evictable()
        await asyncio.gather(s.victim(), s.evictor())

    return case()


def test_planted_race_found_and_replayed_exactly():
    report = schedule.explore(_planted_case, seeds=range(24))
    assert report.failures, "explorer must find the planted interleaving"
    assert any(r.ok for r in report.results), (
        "some schedules must pass — the bug is schedule-dependent, "
        "not deterministic"
    )
    bad = report.failures[0]
    assert bad.error.startswith("KeyError")
    # replay twice: identical verdict AND identical schedule, byte for byte
    again = schedule.run_case(_planted_case, seed=bad.seed)
    third = schedule.run_case(_planted_case, seed=bad.seed)
    assert again.error == third.error == bad.error
    assert again.trace_bytes() == third.trace_bytes() == bad.trace_bytes()


# ----------------------------------- window 1: handle_batch session eviction


def _session_case(n_writers: int = 3, n_handshakes: int = 3):
    """Real MochiReplica.handle_batch under a 1-entry SessionTable: MAC'd
    batches pin client-A while concurrent handshakes force capacity
    evictions.  The invariant (PR-8 pin fix): a batch that AUTHENTICATED a
    MAC'd sender must seal its response under that session — an ack with no
    MAC means the session vanished between auth and response-seal."""
    from mochi_tpu.cluster.config import ClusterConfig
    from mochi_tpu.crypto import session as session_crypto
    from mochi_tpu.crypto.keys import generate_keypair
    from mochi_tpu.net.transport import new_msg_id
    from mochi_tpu.protocol import (
        Envelope,
        NudgeSyncToServer,
        SessionInitToServer,
        SyncAckFromServer,
    )
    from mochi_tpu.server.admission import SessionTable
    from mochi_tpu.server.replica import MochiReplica

    async def case():
        kps = {f"server-{i}": generate_keypair() for i in range(4)}
        config = ClusterConfig.build(
            {sid: f"127.0.0.1:{i + 1}" for i, sid in enumerate(kps)},
            rf=4,
            public_keys={sid: k.public_key for sid, k in kps.items()},
        )
        replica = MochiReplica("server-0", config, kps["server-0"], admission=False)
        replica._sessions = SessionTable(max_entries=1, ttl_s=0)
        session_key = b"\x07" * 32
        replica._sessions["client-A"] = session_key
        acked = []

        def macd_env():
            return session_crypto.seal(
                Envelope(
                    payload=NudgeSyncToServer(("k",)),
                    msg_id=new_msg_id(),
                    sender_id="client-A",
                    timestamp_ms=0,
                ),
                session_key,
            )

        def handshake_env(i):
            hs = session_crypto.new_handshake()
            env = Envelope(
                payload=SessionInitToServer(hs.public_bytes, hs.nonce),
                msg_id=new_msg_id(),
                sender_id=f"client-B{i}",
                timestamp_ms=0,
            )
            kp = generate_keypair()
            return env.with_signature(kp.sign(env.signing_bytes()))

        async def macd_batch(i):
            # every other writer rides in a MIXED batch with a handshake —
            # the exact one-batch window test_overload pins, here explored
            # under perturbed wake order with other batches in flight
            batch = [macd_env()]
            if i % 2:
                batch.append(handshake_env(100 + i))
            responses = await replica.handle_batch(batch)
            if isinstance(responses[0].payload, SyncAckFromServer):
                acked.append(i)
                assert responses[0].mac is not None, (
                    "session evicted between auth and response-seal "
                    "(the pre-PR-8 bug)"
                )

        async def handshake_batch(i):
            await replica.handle_batch([handshake_env(i)])

        try:
            # sequential warm-up batch: guarantees ≥1 authenticated window
            # regardless of how later schedules evict the unpinned session
            await macd_batch(0)
            assert acked, "warm-up batch must authenticate"
            await asyncio.gather(
                *(macd_batch(1 + i) for i in range(n_writers)),
                *(handshake_batch(i) for i in range(n_handshakes)),
            )
        finally:
            await replica.close()

    return case


def test_session_eviction_window_single_seed():
    result = schedule.run_case(_session_case(), seed=3, timeout_s=60)
    assert result.ok, result.error


@pytest.mark.slow
def test_explore_session_eviction_window():
    report = schedule.explore(
        _session_case(), seeds=schedule.exploration_seeds(), timeout_s=120
    )
    assert report.ok, report.summary() + "\n" + "\n".join(
        f"seed {r.seed}: {r.error}" for r in report.failures
    )


# ------------------------------------ window 2: Write1 → reclaim → Write2


def _reclaim_case():
    """The PR-9 grant-TTL window over real DataStores (no sockets): a slow
    writer assembles a full certificate, stalls past the TTL mid-Write2
    while a contender's conflicting Write1 reclaims the aged slots on every
    store, then commits.  Invariants: the self-certifying certificate still
    applies everywhere, the reclaim ledger pins the ORIGINAL grantee's
    hash, and the contender's replacement grants sit strictly above the
    reclaimed slot."""
    from mochi_tpu.cluster import ClusterConfig
    from mochi_tpu.protocol import (
        Action,
        Operation,
        Transaction,
        Write1OkFromServer,
        Write1ToServer,
        Write2AnsFromServer,
        Write2ToServer,
        WriteCertificate,
        transaction_hash,
    )
    from mochi_tpu.server.store import DataStore

    async def case():
        cfg = ClusterConfig.build(
            {f"server-{i}": f"127.0.0.1:{8001 + i}" for i in range(4)}, rf=4
        )
        stores = [DataStore(f"server-{i}", cfg) for i in range(4)]
        key, seed_ts = "hotk", 41
        txn = Transaction((Operation(Action.WRITE, key, b"slow-v"),))
        blind = Transaction((Operation(Action.WRITE, key, None),))
        slow_hash = transaction_hash(txn)
        w1 = Write1ToServer("client-slow", blind, seed_ts, slow_hash)
        grants = {}
        for s in stores:
            r = s.process_write1(w1)
            assert isinstance(r, Write1OkFromServer)
            grants[r.multi_grant.server_id] = r.multi_grant
            await asyncio.sleep(0)  # yield: let schedules interleave
        wc = WriteCertificate(grants)
        granted_ts = next(iter(grants.values())).grants[key].timestamp

        async def contender():
            # stalls past the TTL, then collides with the aged slots
            await asyncio.sleep(0.22)
            c_txn = Transaction((Operation(Action.WRITE, key, b"contend"),))
            c_blind = Transaction((Operation(Action.WRITE, key, None),))
            c_w1 = Write1ToServer(
                "client-b", c_blind, seed_ts, transaction_hash(c_txn)
            )
            for s in stores:
                r = s.process_write1(c_w1)
                if isinstance(r, Write1OkFromServer):
                    # replacement grant strictly above the reclaimed slot
                    assert r.multi_grant.grants[key].timestamp > granted_ts
                await asyncio.sleep(0)

        async def slow_write2():
            await asyncio.sleep(0.45)  # mid-Write2 stall past the TTL
            for s in stores:
                ans = s.process_write2(Write2ToServer(wc, txn))
                assert isinstance(ans, Write2AnsFromServer), ans
                assert ans.result.operations[0].status.name == "OK"
                await asyncio.sleep(0)

        await asyncio.gather(contender(), slow_write2())
        reclaims = sum(s.reclaims for s in stores)
        assert reclaims > 0, "the race never happened — nothing was reclaimed"
        for s in stores:
            # acked write survives reclamation on every store...
            assert s.data[key].value == b"slow-v"
            # ...and every reclaimed slot remembers the ORIGINAL grantee
            for (k, ts), h in s.reclaimed.items():
                if k == key and ts == granted_ts:
                    assert h == slow_hash

    return case


def test_grant_reclaim_window_single_seed(grant_ttl_200ms):
    result = schedule.run_case(_reclaim_case(), seed=7, timeout_s=60)
    assert result.ok, result.error


@pytest.mark.slow
def test_explore_grant_reclaim_window(grant_ttl_200ms):
    report = schedule.explore(
        _reclaim_case(), seeds=schedule.exploration_seeds(), timeout_s=120
    )
    assert report.ok, report.summary() + "\n" + "\n".join(
        f"seed {r.seed}: {r.error}" for r in report.failures
    )


@pytest.fixture
def grant_ttl_200ms():
    from mochi_tpu.server import store as store_mod

    saved = store_mod.GRANT_TTL_MS
    store_mod.GRANT_TTL_MS = 200.0
    try:
        yield
    finally:
        store_mod.GRANT_TTL_MS = saved

"""Byzantine-client harness + the round-13 defenses it forces.

The adversary here is a COORDINATOR with real keys (testing/
byzantine_client.py): every hostile message is validly signed and
protocol-shaped, so what convicts it is accounting — grant-TTL
reclamation, per-client quotas, the replica-side per-client ledger — not
signature checks.  These tests pin the HQ-contention liveness hole the
attacks exploit and the exact bounds the defenses restore.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from mochi_tpu.client.errors import RequestRefused
from mochi_tpu.client.txn import TransactionBuilder
from mochi_tpu.server import store as store_mod
from mochi_tpu.testing import ByzantineClient, InvariantChecker, VirtualCluster
from mochi_tpu.testing.byzantine_client import defense_knobs as _knobs


def run(coro):
    asyncio.run(asyncio.wait_for(coro, timeout=120))


async def _commit_with_retry(client, key, val, deadline_s):
    """App-level retry loop (the benchmark's time-to-conflicting-commit
    probe): retry RequestRefused until the deadline; return elapsed s."""
    t0 = time.monotonic()
    while True:
        try:
            await client.execute_write_transaction(
                TransactionBuilder().write(key, val).build()
            )
            return time.monotonic() - t0
        except RequestRefused:
            if time.monotonic() - t0 > deadline_s:
                raise
            await asyncio.sleep(0.02)


def test_withhold_wedge_reclaimed_within_ttl():
    """The tentpole arc: a withholding client sweeps EVERY subEpoch seed
    of a key's epoch (full wedge — every conflicting Write1 refused at
    any seed), and grant-TTL reclamation un-wedges the honest writer in
    bounded time: conflicting commit lands within ~TTL, reclaim counters
    accrue, the wedge liveness metric records the window, and every
    safety invariant (incl. the new reclaimed-slot rule) holds."""

    async def main():
        # TTL effectively infinite while the wedge is demonstrated (the
        # sweep itself takes longer than a realistic TTL, so a small value
        # would expire the grants before the honest writer ever collides),
        # then dropped so the already-aged grants reclaim on the next
        # conflict — each phase is deterministic.
        with _knobs(ttl_ms=3600e3, quota=0):
            async with VirtualCluster(4, rf=4) as vc:
                checker = InvariantChecker(vc.replicas)
                checker.start(0.02)
                byz = vc.byzantine_client("withhold")
                honest = vc.client(timeout_s=2.0, write_attempts=6)
                held = await byz.wedge("wk")
                # the sweep owns the whole seed space at every replica
                assert held >= 4 * 1000, held
                # phase 1: wedged — every conflicting Write1 refused at
                # whatever seed the honest client draws
                with pytest.raises(RequestRefused):
                    await honest.execute_write_transaction(
                        TransactionBuilder().write("wk", b"good").build()
                    )
                # phase 2: reclamation on — the held grants are now past
                # the TTL, so the next conflict supersedes them and the
                # honest commit lands in bounded time
                store_mod.GRANT_TTL_MS = 250.0
                elapsed = await _commit_with_retry(honest, "wk", b"good", 5.0)
                checker.record_ack("wk", b"good")
                assert elapsed < 2.0, elapsed
                reclaims = sum(r.store.reclaims for r in vc.replicas)
                assert reclaims > 0, "no grant was ever reclaimed"
                # the liveness metric saw the wedge open and close
                assert any(
                    r.store.max_wedge_ms > 0 for r in vc.replicas
                ), [r.store.max_wedge_ms for r in vc.replicas]
                # the withholder is attributed in the per-client ledger
                assert any(
                    r.store.client_stats()["per_client"]
                    .get(byz.client_id, {})
                    .get("reclaimed_from", 0)
                    > 0
                    for r in vc.replicas
                )
                res = await honest.execute_read_transaction(
                    TransactionBuilder().read("wk").build()
                )
                assert res.operations[0].value == b"good"
                await checker.final_check(honest)
                await checker.stop()
                report = checker.report()
                assert report["ok"], report["violations"]
                assert report["grant_reclaims"] == reclaims
                assert report["max_wedge_ms"] > 0

    run(main())


def test_withhold_wedges_forever_without_ttl():
    """The hole the defense closes, demonstrated: with reclamation AND
    quota off (the pre-round-13 posture), the full-seed wedge refuses a
    conflicting honest writer indefinitely — the typed RequestRefused is
    all it ever gets, and the wedge stays open on the admin surface."""

    async def main():
        with _knobs(ttl_ms=0.0, quota=0):
            async with VirtualCluster(4, rf=4) as vc:
                byz = vc.byzantine_client("withhold")
                honest = vc.client(timeout_s=2.0, write_attempts=6)
                assert await byz.wedge("fk") >= 4 * 1000
                with pytest.raises(RequestRefused):
                    await honest.execute_write_transaction(
                        TransactionBuilder().write("fk", b"v").build()
                    )
                st = vc.replicas[0].store.client_stats()
                assert st["open_wedges"] >= 1, st
                assert st["max_open_wedge_ms"] > 0, st
                assert sum(r.store.reclaims for r in vc.replicas) == 0

    run(main())


def test_quota_caps_grant_hoard():
    """grant-hoard vs the per-client quota: a sweep across 64 keys is
    capped at quota outstanding grants per replica, the overflow gets the
    typed QUOTA_EXCEEDED refusal (counted on both sides), and honest
    writers on hoarded keys commit unimpeded."""

    async def main():
        with _knobs(ttl_ms=0.0, quota=16):
            async with VirtualCluster(4, rf=4) as vc:
                byz = vc.byzantine_client("grant-hoard")
                await byz.hoard([f"h-{i}" for i in range(64)])
                assert byz.stats["quota_refused"] > 0, byz.stats
                for r in vc.replicas:
                    st = r.store.client_stats()
                    held = st["per_client"].get(byz.client_id, {})
                    assert held.get("outstanding", 0) <= 16, (r.server_id, held)
                    assert st["quota_refused"] > 0
                    # the replica-side surface counted the typed refusals
                    assert r.client_grant_stats()["quota_refusals_served"] > 0
                honest = vc.client(timeout_s=2.0)
                for i in range(4):
                    await honest.execute_write_transaction(
                        TransactionBuilder().write(f"h-{i}", b"ok").build()
                    )
                    res = await honest.execute_read_transaction(
                        TransactionBuilder().read(f"h-{i}").build()
                    )
                    assert res.operations[0].value == b"ok"

    run(main())


def test_quota_refusal_is_flow_control_for_honest_sdk():
    """An identity at its quota driving the HONEST SDK write path gets
    flow control, not a hang: typed QUOTA_EXCEEDED refusals feed the
    shed-backoff arc and surface as a bounded typed RequestRefused, with
    the client-side quota counters accrued for the admin shell."""

    async def main():
        with _knobs(ttl_ms=0.0, quota=2):
            async with VirtualCluster(4, rf=4) as vc:
                byz = vc.byzantine_client("withhold")
                # exhaust the wrapped identity's quota with held grants
                await byz.acquire("q-a", 7)
                await byz.acquire("q-b", 8)
                with pytest.raises(RequestRefused):
                    # the SAME identity through the production write path
                    await byz.client.execute_write_transaction(
                        TransactionBuilder().write("q-c", b"v").build()
                    )
                assert byz.client.metrics.counters.get("client.write1-quota", 0) > 0
                assert any(
                    name.startswith("client.quota-refused.")
                    for name in byz.client.metrics.counters
                )

    run(main())


def test_quota_counts_wide_transactions():
    """One wide Write1 must not hoard past the quota in a single message:
    the quota counts the request's distinct owned keys too, so a 64-key
    transaction against quota=16 is refused typed with NOTHING issued."""
    from mochi_tpu.protocol import (
        Action,
        FailType,
        Operation,
        RequestFailedFromServer,
        Transaction,
        transaction_hash,
    )

    async def main():
        with _knobs(ttl_ms=0.0, quota=16):
            async with VirtualCluster(4, rf=4) as vc:
                byz = vc.byzantine_client("grant-hoard")
                txn = Transaction(
                    tuple(
                        Operation(Action.WRITE, f"wide-{i}", b"x")
                        for i in range(64)
                    )
                )
                blind = byz.client._write1_transaction(txn)
                info = vc.config.servers["server-0"]
                payload = await byz._write1_one(
                    info, blind, 7, transaction_hash(txn)
                )
                assert isinstance(payload, RequestFailedFromServer), payload
                assert payload.fail_type == FailType.QUOTA_EXCEEDED
                st = vc.replicas[0].store.client_stats()
                held = st["per_client"].get(byz.client_id, {})
                assert held.get("outstanding", 0) == 0, held

    run(main())


def test_quota_exempts_idempotent_retry():
    """A client AT its quota retrying a Write1 whose grants it already
    holds (lost Write1Ok) issues nothing new — the retry must return the
    existing grants, not a QUOTA_EXCEEDED that strands its own in-flight
    write."""

    async def main():
        with _knobs(ttl_ms=0.0, quota=4):
            async with VirtualCluster(4, rf=4) as vc:
                byz = vc.byzantine_client("withhold")
                for i in range(4):
                    grants = await byz.acquire(f"iq-{i}", 7)
                    assert grants, i  # at quota after the 4th
                refused_before = byz.stats["quota_refused"]
                # retry of iq-0 at the same (txn, seed): idempotent, exempt
                again = await byz.acquire("iq-0", 7)
                assert again, "idempotent retry was refused at quota"
                assert byz.stats["quota_refused"] == refused_before
                # ...while a NEW key is still quota-refused
                assert not await byz.acquire("iq-new", 7)
                assert byz.stats["quota_refused"] > refused_before

    run(main())


def test_abandoned_grants_decay_at_quota_pressure():
    """An honest client's ABANDONED grants (no conflicting writer ever
    touches those slots, so the lazy conflict-reclaim never fires) must
    not pin its quota forever: at quota pressure the expiry sweep
    reclaims its TTL-aged grants and the next transaction proceeds."""

    async def main():
        with _knobs(ttl_ms=200.0, quota=4):
            async with VirtualCluster(4, rf=4) as vc:
                byz = vc.byzantine_client("withhold")
                for i in range(4):
                    await byz.acquire(f"dk-{i}", 7)
                # age the residue past the TTL; nothing conflicts with it
                await asyncio.sleep(0.3)
                # the same identity's next write succeeds: the quota path
                # swept the aged grants instead of refusing
                await byz.client.execute_write_transaction(
                    TransactionBuilder().write("dk-new", b"v").build()
                )
                assert sum(r.store.reclaims for r in vc.replicas) > 0
                res = await byz.client.execute_read_transaction(
                    TransactionBuilder().read("dk-new").build()
                )
                assert res.operations[0].value == b"v"

    run(main())


def test_partial_write2_minority_divergence_heals():
    """partial-write2: a fully valid certificate committed at ONE replica
    only.  The minority replica holds a commit the majority never saw —
    replicas diverge on outstanding state — but safety invariants hold
    and an honest writer's quorum still decides reads."""

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            checker = InvariantChecker(vc.replicas)
            checker.start(0.02)
            byz = vc.byzantine_client("partial-write2")
            assert await byz.partial_write2("pk", b"evil", n_targets=1)
            assert byz.stats["partial_commits"] == 1
            # the minority applied it; the majority holds nothing yet
            holders = sum(
                1
                for r in vc.replicas
                if (sv := r.store._get("pk")) is not None and sv.exists
            )
            assert holders >= 1
            assert holders < len(vc.replicas), "partial commit reached everyone?"
            honest = vc.client(timeout_s=2.0)
            elapsed = await _commit_with_retry(honest, "pk", b"good", 10.0)
            checker.record_ack("pk", b"good")
            assert elapsed < 10.0
            res = await honest.execute_read_transaction(
                TransactionBuilder().read("pk").build()
            )
            assert res.operations[0].value == b"good"
            await checker.final_check(honest)
            await checker.stop()
            assert checker.ok, checker.report()["violations"]

    run(main())


def test_seed_bias_contention_and_wedge_metric():
    """seed-bias: the attacker deterministically occupies the seed the
    honest client will draw next (both RNGs pinned), forcing a refusal on
    the first attempt; the honest retry's fresh seed escapes, the commit
    lands, and the store's wedge metric records the contention window."""

    async def main():
        import random

        with _knobs(ttl_ms=0.0, quota=128):
            async with VirtualCluster(4, rf=4) as vc:
                honest = vc.client(timeout_s=2.0)
                honest._rand = random.Random(42)
                first_seed = random.Random(42).randrange(1000)
                byz = vc.byzantine_client("seed-bias")
                await byz.acquire("sb", first_seed, value_hint=b"bias")
                await honest.execute_write_transaction(
                    TransactionBuilder().write("sb", b"good").build()
                )
                res = await honest.execute_read_transaction(
                    TransactionBuilder().read("sb").build()
                )
                assert res.operations[0].value == b"good"
                # the forced first-attempt collision opened (and the retry
                # closed) the wedge window on the key's replicas
                assert any(r.store.max_wedge_ms > 0 for r in vc.replicas)

    run(main())


def test_reclaim_invariant_rule_non_vacuous():
    """Invariant 4 must actually convict: fabricate a reclaimed-slot
    ledger entry — on a replica whose OWN grant sits inside the
    committed certificate (the rule's scope: only the reclaimer's grant
    reappearing under a different hash proves a double-grant) — and
    demand the checker fires."""

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("rv", b"v0").build()
            )
            checker = InvariantChecker(vc.replicas)
            checker.check_now()
            assert checker.ok
            holder = next(
                r
                for r in vc.replicas
                if (sv := r.store._get("rv")) is not None
                and sv.current_certificate is not None
            )
            cert = holder.store._get("rv").current_certificate
            # a replica that SIGNED the certificate fabricates the ledger
            replica = vc.replica(next(iter(cert.grants)))
            ts = holder.store._cert_ts(holder.store._get("rv"))
            assert ts is not None
            replica.store.reclaimed[("rv", ts)] = b"\x13" * 64
            checker.check_now()
            report = checker.report()
            assert not report["ok"]
            assert any("reclaimed slot" in v for v in report["violations"])
            # ...and the sound scope: a ledger entry on a replica whose
            # grant is NOT in the certificate convicts nobody (honest
            # cross-replica slot coexistence is legal)
            outsiders = [
                r for r in vc.replicas if r.server_id not in cert.grants
            ]
            if outsiders:
                checker2 = InvariantChecker(vc.replicas)
                outsiders[0].store.reclaimed[("rv", ts)] = b"\x17" * 64
                checker2.check_now()
                ok_violations = [
                    v
                    for v in checker2.report()["violations"]
                    if "reclaimed slot" in v and outsiders[0].server_id in v
                ]
                assert not ok_violations, ok_violations

    run(main())

"""Configstamp-gated live reconfiguration (paper mochiDB.tex:184-199 —
declared but never implemented in the reference; VERDICT r1 task 9).

The membership document lives at CONFIG_CLUSTER_KEY, commits through the
standard 2-phase write (every server owns the _CONFIG_ keyspace), and each
replica's apply hook installs it live.  Clients refresh on demand or
automatically when a cross-config write fails.
"""

import asyncio

from mochi_tpu.client import MochiDBClient, TransactionBuilder
from mochi_tpu.cluster.config import CONFIG_CLUSTER_KEY, ClusterConfig
from mochi_tpu.crypto.keys import generate_keypair
from mochi_tpu.server.replica import MochiReplica
from mochi_tpu.testing import VirtualCluster


def run(coro):
    asyncio.run(asyncio.wait_for(coro, timeout=120))


def current_servers(vc):
    return {r.server_id: f"{vc.host}:{r.bound_port}" for r in vc.replicas}


def test_commit_config_installs_on_all_replicas():
    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("pre", b"v").build()
            )
            new_cfg = vc.config.evolve(current_servers(vc))  # same members, cs+1
            await client.reconfigure_cluster(new_cfg)
            for r in vc.replicas:
                assert r.config.configstamp == new_cfg.configstamp, r.server_id
            # traffic continues under the new configstamp
            await client.execute_write_transaction(
                TransactionBuilder().write("post", b"w").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("pre").read("post").build()
            )
            assert [r.value for r in res.operations] == [b"v", b"w"]

    run(main())


def test_stale_client_auto_refreshes_after_reconfig():
    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            admin = vc.client()
            stale = vc.client()
            await stale.execute_write_transaction(
                TransactionBuilder().write("k0", b"v0").build()
            )
            await admin.reconfigure_cluster(vc.config.evolve(current_servers(vc)))
            # The stale client still holds cs=1; its Write1 grants will carry
            # the NEW configstamp (replicas already switched), its own config
            # check passes... the cross-config path it must survive is a
            # full write + the refresh_config fallback.
            await stale.execute_write_transaction(
                TransactionBuilder().write("k1", b"v1").build()
            )
            res = await stale.execute_read_transaction(
                TransactionBuilder().read("k1").build()
            )
            assert res.operations[0].value == b"v1"
            assert await stale.refresh_config() or stale.config.configstamp >= 2

    run(main())


def test_add_server_live():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            for i in range(12):
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"pre-{i}", b"v%d" % i).build()
                )

            # boot the 5th server with the NEW (cs=2) config
            kp5 = generate_keypair()
            servers = current_servers(vc)
            # reserve a port by starting the replica on port 0 with a
            # placeholder config, then evolving with its bound port
            new_replica = MochiReplica(
                server_id="server-4",
                config=vc.config,  # placeholder until install
                keypair=kp5,
                client_public_keys=vc.client_keys,
                host=vc.host,
                port=0,
            )
            await new_replica.start()
            servers["server-4"] = f"{vc.host}:{new_replica.bound_port}"
            new_cfg = vc.config.evolve(
                servers, public_keys={"server-4": kp5.public_key}
            )
            new_replica.config = new_cfg
            new_replica.store.config = new_cfg
            vc.replicas.append(new_replica)
            vc.keypairs["server-4"] = kp5

            await client.reconfigure_cluster(new_cfg)
            for r in vc.replicas[:4]:
                assert r.config.configstamp == new_cfg.configstamp

            # new member pulls its keys from peers
            await new_replica.resync()
            owned = [f"pre-{i}" for i in range(12) if new_replica.store.owns(f"pre-{i}")]
            assert owned, "5-server ring should give server-4 some pre keys"
            for key in owned:
                sv = new_replica.store._get(key)
                assert sv is not None and sv.exists, key

            # writes keyed to sets including the new server work
            for i in range(12):
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"post-{i}", b"w%d" % i).build()
                )
                res = await client.execute_read_transaction(
                    TransactionBuilder().read(f"post-{i}").build()
                )
                assert res.operations[0].value == b"w%d" % i

    run(main())


def test_reconfig_registers_new_identity_with_verifier():
    """Adding a server live also registers its identity with a
    comb-capable verifier (crypto/comb.py) — new-member certificates take
    the fast path instead of silently staying on the general ladder."""

    class RecordingVerifier:
        def __init__(self):
            self.registered = []

        async def verify_batch(self, items):
            from mochi_tpu.crypto import keys as _k

            return [
                _k.verify(it.public_key, it.message, it.signature)
                for it in items
            ]

        def register_signers(self, pubs):
            self.registered.extend(bytes(p) for p in pubs)

        async def close(self):
            pass

    verifiers = []

    def factory():
        v = RecordingVerifier()
        verifiers.append(v)
        return v

    async def main():
        async with VirtualCluster(4, rf=4, verifier_factory=factory) as vc:
            client = vc.client()
            kp5 = generate_keypair()
            servers = current_servers(vc)
            new_replica = MochiReplica(
                server_id="server-4",
                config=vc.config,
                keypair=kp5,
                client_public_keys=vc.client_keys,
                host=vc.host,
                port=0,
            )
            await new_replica.start()
            servers["server-4"] = f"{vc.host}:{new_replica.bound_port}"
            new_cfg = vc.config.evolve(
                servers, public_keys={"server-4": kp5.public_key}
            )
            new_replica.config = new_cfg
            new_replica.store.config = new_cfg
            vc.replicas.append(new_replica)
            vc.keypairs["server-4"] = kp5
            await client.reconfigure_cluster(new_cfg)
            for v in verifiers:
                assert kp5.public_key in v.registered

    run(main())


def test_remove_server_live():
    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("rk", b"v").build()
            )
            servers = current_servers(vc)
            del servers["server-4"]
            new_cfg = vc.config.evolve(servers)
            await client.reconfigure_cluster(new_cfg)

            retired = vc.replica("server-4")
            assert retired.config.configstamp == new_cfg.configstamp
            assert "server-4" not in retired.config.servers

            # cluster keeps serving with 4 members; retired server answers
            # WRONG_SHARD (owns nothing)
            await client.execute_write_transaction(
                TransactionBuilder().write("rk2", b"v2").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("rk").read("rk2").build()
            )
            assert [r.value for r in res.operations] == [b"v", b"v2"]
            assert not retired.store.owns("rk2")

    run(main())


def test_configstamp_gating_rejects_mixed_certificates():
    from mochi_tpu.cluster.config import ClusterConfig as CC
    from mochi_tpu.protocol import (
        Grant, MultiGrant, Status, Transaction, Operation, Action,
        Write2ToServer, WriteCertificate, RequestFailedFromServer,
        transaction_hash,
    )
    from mochi_tpu.server.store import DataStore

    cfg = CC.build({f"server-{i}": f"127.0.0.1:{9200+i}" for i in range(4)}, rf=4)
    ds = DataStore("server-0", cfg)
    txn = Transaction((Operation(Action.WRITE, "k", b"v"),))
    h = transaction_hash(txn)

    def mg(sid, cs):
        return MultiGrant({"k": Grant("k", 500, cs, h, Status.OK)}, "c", sid)

    # mixed configstamps -> rejected
    wc = WriteCertificate({"server-0": mg("server-0", 1), "server-1": mg("server-1", 2),
                           "server-2": mg("server-2", 1)})
    resp = ds.process_write2(Write2ToServer(wc, txn))
    assert isinstance(resp, RequestFailedFromServer)

    # configstamp ahead of the replica -> rejected with the ahead marker
    wc = WriteCertificate({f"server-{i}": mg(f"server-{i}", 7) for i in range(3)})
    resp = ds.process_write2(Write2ToServer(wc, txn))
    assert isinstance(resp, RequestFailedFromServer)
    assert "configstamp ahead" in resp.detail

    # uniform current configstamp -> applies
    wc = WriteCertificate({f"server-{i}": mg(f"server-{i}", 1) for i in range(3)})
    resp = ds.process_write2(Write2ToServer(wc, txn))
    assert not isinstance(resp, RequestFailedFromServer)


def test_fresh_member_bootstraps_history_from_archive():
    """A server that never saw configstamp 1 (booted at cs=2, after a
    remove+add reconfiguration) must still import pre-reconfig data: it
    learns the cs=1 config from the committed archive (resync pulls the
    _CONFIG_ keyspace first) and validates historical certificates against
    it — including grants signed by the since-removed member."""

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            for i in range(10):
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"old-{i}", b"v%d" % i).build()
                )

            # one reconfiguration: remove server-4, add server-5
            kp6 = generate_keypair()
            servers = current_servers(vc)
            del servers["server-4"]
            newcomer = MochiReplica(
                server_id="server-5",
                config=vc.config,  # placeholder
                keypair=kp6,
                client_public_keys=vc.client_keys,
                host=vc.host,
                port=0,
            )
            await newcomer.start()
            servers["server-5"] = f"{vc.host}:{newcomer.bound_port}"
            new_cfg = vc.config.evolve(servers, public_keys={"server-5": kp6.public_key})
            # the newcomer boots knowing ONLY cs=2 — no cs=1 in its history
            newcomer.config = new_cfg
            newcomer.store.config = new_cfg
            newcomer.store.config_history = {new_cfg.configstamp: new_cfg}
            vc.replicas.append(newcomer)
            vc.keypairs["server-5"] = kp6

            await client.reconfigure_cluster(new_cfg)
            n = await newcomer.resync()
            assert 1 in newcomer.store.config_history, "archive not learned"

            owned = [
                f"old-{i}" for i in range(10) if newcomer.store.owns(f"old-{i}")
            ]
            assert owned, "newcomer should own some moved keys"
            for key in owned:
                sv = newcomer.store._get(key)
                assert sv is not None and sv.exists, (key, n)

    run(main())


def test_admin_gating_blocks_non_admin_reconfig():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            admin = vc.client()
            rogue = vc.client()
            # lock the config keyspace to the admin's key (replicas share
            # the config object, so this mutation reaches all of them)
            vc.config.admin_keys.append(admin.keypair.public_key)

            # rogue (registered, valid signatures, but not an admin) is denied
            try:
                await rogue.reconfigure_cluster(vc.config.evolve(current_servers(vc)))
                raise AssertionError("rogue reconfig should have failed")
            except AssertionError:
                raise
            except Exception:
                pass
            for r in vc.replicas:
                assert r.config.configstamp == 1
                assert r.metrics.counters.get("replica.admin-denied", 0) >= 1

            # the admin key goes through
            await admin.reconfigure_cluster(vc.config.evolve(current_servers(vc)))
            for r in vc.replicas:
                assert r.config.configstamp == 2

            # ordinary data traffic is unaffected by admin gating
            await rogue.execute_write_transaction(
                TransactionBuilder().write("plain", b"ok").build()
            )

    run(main())


def test_laggard_catches_up_after_two_missed_reconfigs():
    """A replica offline through cs=1->2->3 must walk the archive catch-up
    chain (each rung's certificate is stamped with the PREVIOUS config) and
    end at cs=3 with its data — the permanent-wedge scenario from review."""

    async def main():
        async with VirtualCluster(5, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("survivor", b"v1").build()
            )
            # take server-4 down; reconfigure TWICE without it (membership
            # unchanged — stamps 2 and 3)
            victim = vc.replica("server-4")
            victim_port = victim.bound_port
            await victim.close()
            urls = {sid: info.url for sid, info in vc.config.servers.items()}
            await client.reconfigure_cluster(vc.config.evolve(urls))
            client.config = vc.replicas[0].config
            await client.reconfigure_cluster(client.config.evolve(urls))
            assert vc.replicas[0].config.configstamp == 3

            # server-4 comes back at cs=1 and resyncs
            fresh = MochiReplica(
                server_id="server-4",
                config=ClusterConfig.from_json(victim.config.to_json())
                if victim.config.configstamp == 1
                else vc.config,
                keypair=vc.keypairs["server-4"],
                client_public_keys=vc.client_keys,
                host=vc.host,
                port=victim_port,
            )
            # force its view back to cs=1 regardless of shared-object drift
            base = ClusterConfig.from_json(vc.config.to_json())
            base.configstamp = 1
            fresh.config = base
            fresh.store.config = base
            fresh.store.config_history = {1: base}
            await fresh.start()
            vc.replicas[vc.replicas.index(victim)] = fresh

            # the AUTOMATIC path: a targeted config resync (what the
            # configstamp-ahead nudge schedules) must fetch the archive
            # rungs even though it names only the head document
            await fresh.resync(keys=(CONFIG_CLUSTER_KEY,))
            assert fresh.config.configstamp == 3, fresh.config.configstamp
            # then data follows on a full sweep
            await fresh.resync()
            sv = fresh.store._get("survivor")
            assert sv is not None and sv.exists

    run(main())


def test_non_sequential_config_write_rejected():
    """A concurrent/stale admin commit whose document stamp is not current
    or current+1 must be refused — otherwise the stored membership document
    diverges from what replicas installed (split-brain from review)."""
    from mochi_tpu.protocol import (
        Action, Grant, MultiGrant, Operation, RequestFailedFromServer,
        Status, Transaction, Write2ToServer, WriteCertificate,
        transaction_hash,
    )
    from mochi_tpu.server.store import DataStore

    cfg = ClusterConfig.build(
        {f"s{i}": f"127.0.0.1:{9400+i}" for i in range(4)}, rf=4
    )
    ds = DataStore("s0", cfg)
    bad_doc = ClusterConfig.build(
        {f"s{i}": f"127.0.0.1:{9400+i}" for i in range(4)}, rf=4
    )
    bad_doc.configstamp = 7  # far from current 1
    txn = Transaction(
        (Operation(Action.WRITE, CONFIG_CLUSTER_KEY, bad_doc.to_json().encode()),)
    )
    h = transaction_hash(txn)
    wc = WriteCertificate({
        f"s{i}": MultiGrant(
            {CONFIG_CLUSTER_KEY: Grant(CONFIG_CLUSTER_KEY, 500, 1, h, Status.OK)},
            "c", f"s{i}",
        )
        for i in range(3)
    })
    resp = ds.process_write2(Write2ToServer(wc, txn))
    assert isinstance(resp, RequestFailedFromServer)
    assert "non-sequential" in resp.detail


def test_admin_gating_covers_client_registry():
    """_CONFIG_CLIENT_* writes are admin-gated when admin_keys is set: an
    ordinary registered client must NOT be able to overwrite another
    client's key binding (impersonation), while the admin key can — and a
    registry rotation drops the victim's live MAC session."""

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            admin = vc.client()
            rogue = vc.client()
            victim = vc.client()
            # establish victim sessions, then lock the config keyspace
            await victim.execute_write_transaction(
                TransactionBuilder().write("v", b"1").build()
            )
            vc.config.admin_keys.append(admin.keypair.public_key)

            try:
                await rogue.register_client_key(victim.client_id, bytes(32))
                raise AssertionError("non-admin registry write should fail")
            except AssertionError:
                raise
            except Exception:
                pass

            assert victim.client_id in vc.replicas[0]._sessions
            await admin.register_client_key(
                victim.client_id, victim.keypair.public_key
            )
            # rotation hook: victim's sessions were dropped on every replica
            for r in vc.replicas:
                assert victim.client_id not in r._sessions
            # and the victim transparently re-handshakes
            await victim.execute_write_transaction(
                TransactionBuilder().write("v", b"2").build()
            )
            res = await victim.execute_read_transaction(
                TransactionBuilder().read("v").build()
            )
            assert res.operations[0].value == b"2"

    run(main())


def test_evolve_carries_keys_and_bumps_stamp():
    kp = generate_keypair()
    cfg = ClusterConfig.build(
        {f"s{i}": f"127.0.0.1:{9300+i}" for i in range(4)},
        rf=4,
        public_keys={f"s{i}": kp.public_key for i in range(4)},
    )
    grown = cfg.evolve(
        {**{f"s{i}": f"127.0.0.1:{9300+i}" for i in range(4)}, "s4": "127.0.0.1:9304"},
        public_keys={"s4": kp.public_key},
    )
    assert grown.configstamp == cfg.configstamp + 1
    assert set(grown.servers) == {f"s{i}" for i in range(5)}
    assert grown.public_keys["s0"] == kp.public_key
    shrunk = grown.evolve({f"s{i}": f"127.0.0.1:{9300+i}" for i in range(4)})
    assert shrunk.configstamp == grown.configstamp + 1
    assert "s4" not in shrunk.public_keys


def test_reconfig_at_scale_n16():
    """Live removal from an n=16 rf=16 (f=5, quorum=11) cluster — the
    round-5 large-cluster shape.  Quorum math shifts under reconfiguration
    (rf 16 -> 15: f=(15-1)//3=4, quorum 9), and the archive/configstamp
    chain must hold when every server owns every key.  Pre-reconfig data
    stays readable and new writes commit with the NEW quorum size."""

    async def main():
        async with VirtualCluster(16, rf=16) as vc:
            assert vc.config.f == 5 and vc.config.quorum == 11
            client = vc.client(timeout_s=30.0)
            await client.execute_write_transaction(
                TransactionBuilder().write("big-rk", b"v").build()
            )
            servers = current_servers(vc)
            del servers["server-15"]
            new_cfg = vc.config.evolve(servers, rf=15)
            assert new_cfg.f == 4 and new_cfg.quorum == 9
            await client.reconfigure_cluster(new_cfg)

            # pre-reconfig key readable; new write commits under new quorum
            await client.execute_write_transaction(
                TransactionBuilder().write("big-rk2", b"w").build()
            )
            res = await client.execute_read_transaction(
                TransactionBuilder().read("big-rk").read("big-rk2").build()
            )
            assert [r.value for r in res.operations] == [b"v", b"w"]
            cert = res.operations[1].current_certificate
            assert cert is not None and len(cert.grants) == new_cfg.quorum
            retired = vc.replica("server-15")
            assert "server-15" not in retired.config.servers

    run(main())
